"""Recompilation sentinel: the round program traces exactly once.

The dynamic half of the tracing-hazard gate (static half:
fedtorch_tpu.lint, tests/test_lint_*.py).  PR 1's chaos/guard
machinery and the bench path both rest on "static config => unchanged
traced program"; these tests make that contract executable: the FedAvg
and SCAFFOLD round functions must trace exactly once across multiple
rounds — fault-free AND under a chaos+guard schedule — and any future
change that sneaks a retrace into the hot loop fails here.
"""
import jax
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
    ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.utils import (
    RecompilationSentinel, instrument_trace, jit_cache_size,
)


def make_trainer(algorithm="fedavg", fault_kw=None, num_clients=8):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=10,
                        batch_size=16, synthetic_alpha=0.5,
                        synthetic_beta=0.5),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients, num_comms=5,
            online_client_rate=0.5, algorithm=algorithm,
            sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.05, weight_decay=0.0),
        train=TrainConfig(local_step=3),
        fault=FaultConfig(**(fault_kw or {})),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    alg = make_algorithm(cfg)
    return FederatedTrainer(cfg, model, alg, data.train)


@pytest.mark.parametrize("algorithm", ["fedavg", "scaffold"])
def test_round_traces_exactly_once(algorithm):
    """3+ rounds of the hot path: ONE trace, ONE compiled program."""
    trainer = make_trainer(algorithm)
    server, clients = trainer.init_state(jax.random.key(0))
    with RecompilationSentinel() as s:
        for _ in range(3):
            server, clients, _ = trainer.run_round(server, clients)
        # by round 3 every input is a committed device-resident
        # donated output; the executable cache must stop growing
        # (the first rounds add a fresh-input vs steady-state entry
        # pair without retracing — the jaxpr is reused)
        cache_steady = jit_cache_size(trainer._round_jit)
        for _ in range(2):
            server, clients, metrics = trainer.run_round(server, clients)
        jax.block_until_ready(server.params)
    s.assert_traces(trainer.trace_name, expected=1)
    assert s.count(f"federated.round[{algorithm}]") == 1
    cache_end = jit_cache_size(trainer._round_jit)
    assert cache_end == cache_steady  # None == None when unavailable


@pytest.mark.parametrize("algorithm", ["fedavg", "scaffold"])
def test_round_traces_once_under_faults(algorithm):
    """Chaos + guards are static config: the faulted round program
    must also trace exactly once across rounds — the contract the
    robustness layer (PR 1) depends on."""
    trainer = make_trainer(algorithm, fault_kw=dict(
        client_drop_rate=0.25, straggler_rate=0.25,
        straggler_step_frac=0.5, nan_inject_rate=0.25,
        guard_updates=True))
    server, clients = trainer.init_state(jax.random.key(1))
    with RecompilationSentinel() as s:
        for _ in range(3):
            server, clients, metrics = trainer.run_round(server, clients)
        jax.block_until_ready(server.params)
    s.assert_traces(trainer.trace_name, expected=1)


def test_round_traces_once_with_lifecycle_armed():
    """Process lifecycle (ISSUE 4) is host-only: with the stall
    watchdog armed AND a stop signal folded into the per-round scalar
    fetch, the round program still traces exactly once — the 'zero
    overhead when off, host-only when on' contract (the static half —
    byte-identical HLO — is pinned by test_preemption.py)."""
    from fedtorch_tpu.robustness import StallWatchdog

    trainer = make_trainer(
        "fedavg", fault_kw=dict(watchdog_timeout_s=60.0))
    trainer.attach_stop_signal(lambda: False)
    server, clients = trainer.init_state(jax.random.key(3))
    with StallWatchdog(60.0, exit_fn=lambda code: None) as wd:
        with RecompilationSentinel() as s:
            for r in range(3):
                server, clients, metrics = trainer.run_round(
                    server, clients)
                sc = trainer.round_host_scalars(clients, metrics)
                assert sc["stop"] == 0.0
                wd.heartbeat(r)
    s.assert_traces(trainer.trace_name, expected=1)
    assert not wd.fired


def test_sentinel_catches_retraces():
    """Positive control: the sentinel machinery itself must see a
    retrace when one genuinely happens (new shape => new trace)."""
    import jax.numpy as jnp

    @jax.jit
    @instrument_trace("sentinel_test.f")
    def f(x):
        return jnp.sum(x * 2)

    with RecompilationSentinel() as s:
        f(jnp.ones((4,)))
        f(jnp.ones((4,)))      # cached: no retrace
        f(jnp.ones((8,)))      # new shape: retrace
    assert s.count("sentinel_test.f") == 2
    with pytest.raises(AssertionError, match="traced 2x"):
        s.assert_traces("sentinel_test.f", expected=1)


def test_sentinel_scoping_and_nesting():
    """Counts are scoped to the context: events before/after the
    block are invisible, and sentinels nest independently."""
    import jax.numpy as jnp

    @jax.jit
    @instrument_trace("sentinel_test.g")
    def g(x):
        return x + 1

    g(jnp.ones((2,)))  # traced outside any sentinel
    with RecompilationSentinel() as outer:
        g(jnp.ones((2,)))  # cached — no event
        with RecompilationSentinel() as inner:
            g(jnp.ones((3,)))  # retrace — seen by both
        g(jnp.ones((5,)))      # retrace — seen by outer only
    assert inner.count("sentinel_test.g") == 1
    assert outer.count("sentinel_test.g") == 2


def test_run_rounds_scan_driver_traces_once():
    """The multi-round lax.scan driver is its own single-trace
    program (and does not re-trace the per-round program)."""
    trainer = make_trainer("fedavg")
    server, clients = trainer.init_state(jax.random.key(2))
    with RecompilationSentinel() as s:
        server, clients, ms = trainer.run_rounds(server, clients, 3)
        jax.block_until_ready(server.params)
        server, clients, ms = trainer.run_rounds(server, clients, 3)
        jax.block_until_ready(server.params)
    assert s.count("federated.rounds[fedavg]x3") == 1
    # the scan body inlines round_fn directly — the per-round jit
    # entry must not have been traced at all by the scan driver
    assert s.count(trainer.trace_name) == 0
