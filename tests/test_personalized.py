"""Personalized algorithms: APFL, PerFedMe, PerFedAvg."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig, OptimConfig,
    TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import (
    FederatedTrainer, evaluate, evaluate_personal,
)


def _trainer(algorithm, lr=0.3, local_step=5, num_clients=8, rate=1.0,
             **fed_kw):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20,
                        batch_size=16, synthetic_alpha=1.0,
                        synthetic_beta=1.0),
        federated=FederatedConfig(federated=True, num_clients=num_clients,
                                  online_client_rate=rate,
                                  algorithm=algorithm,
                                  sync_type="local_step", **fed_kw),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=lr, weight_decay=0.0),
        train=TrainConfig(local_step=local_step),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=16)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data.train,
                               val_data=data.val)
    return trainer, data


def _run(trainer, rounds, seed=0):
    server, clients = trainer.init_state(jax.random.key(seed))
    for _ in range(rounds):
        server, clients, metrics = trainer.run_round(server, clients)
    return server, clients, metrics


class TestAPFL:
    def test_personal_config_coercion(self):
        trainer, data = _trainer("apfl")
        assert trainer.cfg.federated.personal  # parameters.py:257-259
        assert data.val is not None

    def test_personal_model_diverges_from_local(self):
        trainer, data = _trainer("apfl")
        server, clients, _ = _run(trainer, 5)
        personal = clients.aux["personal"]
        for pp, lp in zip(jax.tree.leaves(personal),
                          jax.tree.leaves(clients.params)):
            assert not np.allclose(np.asarray(pp), np.asarray(lp))

    def test_converges_and_personal_eval(self):
        trainer, data = _trainer("apfl")
        server, clients, _ = _run(trainer, 12)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.5
        # personal eval on per-client val shards beats random
        losses, accs, summary = evaluate_personal(
            trainer.model, clients.aux, clients.params, trainer.val_data,
            "apfl")
        assert summary["acc_mean"] > 0.5

    def test_adaptive_alpha_moves_and_syncs(self):
        trainer, data = _trainer("apfl", adaptive_alpha=True)
        server, clients, _ = _run(trainer, 3)
        alphas = np.asarray(clients.aux["alpha"])
        # all online clients share the averaged alpha; it moved from 0.5
        assert len(np.unique(np.round(alphas, 6))) <= 2
        assert not np.allclose(alphas, 0.5)
        assert np.all((alphas >= 0) & (alphas <= 1))


class TestPerFedMe:
    def test_w_updates_every_5_steps(self):
        """With local_step=4 (no multiple of 5 inside, but sync at end),
        w must still move exactly at the final step."""
        trainer, _ = _trainer("perfedme", local_step=4)
        server, clients, _ = _run(trainer, 1)
        # after one round the server model must have moved (w stepped at
        # sync even though 4 < 5)
        init_server, _ = trainer.init_state(jax.random.key(0))
        moved = any(
            not np.allclose(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(server.params),
                            jax.tree.leaves(init_server.params)))
        assert moved

    def test_converges_personal(self):
        trainer, data = _trainer("perfedme", lr=0.1,
                                 perfedme_lambda=15.0, local_step=10)
        server, clients, _ = _run(trainer, 12)
        losses, accs, summary = evaluate_personal(
            trainer.model, clients.aux, clients.params, trainer.val_data,
            "perfedme")
        assert summary["acc_mean"] > 0.5


class TestPerFedAvg:
    def test_requires_val_data(self):
        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", batch_size=16),
            federated=FederatedConfig(federated=True, num_clients=4,
                                      algorithm="perfedavg"),
            model=ModelConfig(arch="logistic_regression"),
        ).finalize()
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=16)
        with pytest.raises(ValueError, match="validation batches"):
            FederatedTrainer(cfg, model, make_algorithm(cfg), data.train,
                             val_data=None)

    def test_converges(self):
        trainer, data = _trainer("perfedavg", perfedavg_beta=0.05)
        server, clients, _ = _run(trainer, 12)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.5


def test_alpha_update_matches_reference_formula():
    """flow_utils.py:240-250 hand-check on tiny tensors."""
    cfg = ExperimentConfig(
        federated=FederatedConfig(federated=True, algorithm="apfl",
                                  adaptive_alpha=True, num_clients=1,
                                  online_client_rate=1.0),
        data=DataConfig(dataset="synthetic", synthetic_dim=2,
                        batch_size=2),
        optim=OptimConfig(lr=0.1),
    ).finalize()
    import sys
    sys.path.insert(0, "/root/reference")
    import torch
    pytest.importorskip(
        "fedtorch",
        reason="reference checkout not mounted at /root/reference")
    from fedtorch.comms.utils.flow_utils import alpha_update

    # tiny linear models: 1 param leaf w [2,1]; loss = CE on 2 classes
    class TorchLin(torch.nn.Module):
        def __init__(self, w):
            super().__init__()
            self.fc = torch.nn.Linear(2, 2, bias=False)
            with torch.no_grad():
                self.fc.weight.copy_(torch.tensor(w))

        def forward(self, x):
            return self.fc(x)

    w_l = np.asarray([[0.3, -0.2], [0.1, 0.4]], np.float32)
    w_p = np.asarray([[0.5, 0.0], [-0.1, 0.2]], np.float32)
    x_np = np.asarray([[1.0, 2.0], [0.5, -1.0]], np.float32)
    y_np = np.asarray([0, 1])
    alpha, eta = 0.5, 0.1

    m_l, m_p = TorchLin(w_l), TorchLin(w_p)
    crit = torch.nn.CrossEntropyLoss()
    out = alpha * m_p(torch.tensor(x_np)) \
        + (1 - alpha) * m_l(torch.tensor(x_np))
    loss = crit(out, torch.tensor(y_np))
    loss.backward()
    ref_alpha = alpha_update(m_l, m_p, alpha, eta)

    # ours: same math in jax via the APFL hook internals
    from fedtorch_tpu.algorithms.apfl import APFL
    from fedtorch_tpu.core.losses import softmax_cross_entropy
    alg = APFL(cfg)

    def mixed(pp, lp, a):
        out = a * (x_np @ np.asarray(pp).T) \
            + (1 - a) * (x_np @ np.asarray(lp).T)
        return out

    import jax
    f = lambda pp, lp: softmax_cross_entropy(
        alpha * (jnp.asarray(x_np) @ pp.T)
        + (1 - alpha) * (jnp.asarray(x_np) @ lp.T), jnp.asarray(y_np))
    g_p = jax.grad(f, argnums=0)(jnp.asarray(w_p), jnp.asarray(w_l))
    g_l = jax.grad(f, argnums=1)(jnp.asarray(w_p), jnp.asarray(w_l))
    grad_alpha = float(jnp.vdot(jnp.asarray(w_p - w_l),
                                alpha * g_p + (1 - alpha) * g_l)) \
        + 0.02 * alpha
    ours = float(np.clip(alpha - eta * grad_alpha, 0, 1))
    assert ours == pytest.approx(float(ref_alpha), rel=1e-4)
