"""Recurrent models through the full federated engine.

The reference's centered loops run the Shakespeare GRU for the fedavg
family, AFL, and DRFA with a per-round hidden re-init
(centered/main.py:96-97, centered/drfa.py:94-95); auxiliary inferences
start from a fresh hidden (centered/drfa.py:242). These tests pin the
engine's rnn-carry threading plus every algorithm that runs its own
forwards (APFL, PerFedMe, PerFedAvg, DRFA) on a char-level token task.
"""
import numpy as np
import jax
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, MeshConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.data.batching import ClientData
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer, evaluate_personal

VOCAB, SEQ, C, N = 12, 10, 4, 24


def _token_data(seed=0, n=N, num_clients=C):
    """Tiny shakespeare-shaped dataset: next-token targets on a cyclic
    alphabet, so the GRU has learnable structure."""
    rng = np.random.RandomState(seed)
    starts = rng.randint(0, VOCAB, size=(num_clients, n, 1))
    seq = (starts + np.arange(SEQ + 1)[None, None, :]) % VOCAB
    x = seq[..., :-1].astype(np.int32)
    y = seq[..., 1:].astype(np.int32)
    sizes = np.full((num_clients,), n, np.int32)
    return ClientData(x=x, y=y, sizes=sizes)


def _cfg(algorithm, **fed_kw):
    return ExperimentConfig(
        data=DataConfig(dataset="shakespeare", batch_size=6),
        federated=FederatedConfig(federated=True, num_clients=C,
                                  online_client_rate=1.0,
                                  algorithm=algorithm,
                                  sync_type="local_step", **fed_kw),
        model=ModelConfig(arch="rnn", vocab_size=VOCAB, rnn_seq_len=SEQ,
                          rnn_hidden_size=16),
        optim=OptimConfig(lr=0.3, weight_decay=0.0),
        train=TrainConfig(local_step=4),
        mesh=MeshConfig(num_devices=1),
    ).finalize()


def _trainer(algorithm, **fed_kw):
    cfg = _cfg(algorithm, **fed_kw)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    data = _token_data()
    val = _token_data(seed=1, n=8) if fed_kw.get("personal") else None
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data,
                            val_data=val)


def test_fedavg_rnn_round_learns():
    """Engine carry threading: loss must drop on the cyclic-token task."""
    t = _trainer("fedavg")
    server, clients = t.init_state(jax.random.key(0))
    first = last = None
    for _ in range(10):
        server, clients, m = t.run_round(server, clients)
        loss = float(m.train_loss.sum()) / C
        if first is None:
            first = loss
        last = loss
    assert np.isfinite(last)
    assert last < first * 0.8, (first, last)


@pytest.mark.parametrize("algorithm,fed_kw", [
    ("apfl", {"personal": True}),
    ("perfedme", {"personal": True}),
    ("perfedavg", {"personal": True}),
    ("afl", {}),
    ("fedavg", {"drfa": True}),
])
def test_rnn_supported_across_algorithms(algorithm, fed_kw):
    """Every formerly-restricted algorithm must run the GRU end to end
    with finite losses (VERDICT r1 item 8)."""
    t = _trainer(algorithm, **fed_kw)
    server, clients = t.init_state(jax.random.key(1))
    for _ in range(3):
        server, clients, m = t.run_round(server, clients)
    loss = float(m.train_loss.sum()) / C
    assert np.isfinite(loss), (algorithm, loss)


def test_apfl_rnn_personal_evaluation():
    """The mixed personal/local inference must handle the hidden carry."""
    t = _trainer("apfl", personal=True)
    server, clients = t.init_state(jax.random.key(2))
    server, clients, _ = t.run_round(server, clients)
    losses, accs, summary = evaluate_personal(
        t.model, clients.aux, clients.params, t.val_data, "apfl",
        batch_size=6, max_batches=2)
    assert np.isfinite(summary["loss_mean"])
    assert 0.0 <= summary["acc_mean"] <= 1.0


def test_rnn_carry_not_persisted_in_client_state():
    """The hidden carry is rebuilt from zeros INSIDE each round program
    (federated.py carry0 = init_carry); ClientState has no slot that
    could persist it across rounds — which is exactly the reference's
    per-round init_hidden semantics (centered/main.py:96-97)."""
    from fedtorch_tpu.core.state import ClientState as CS

    assert CS._fields == ("params", "opt", "aux", "epoch", "local_index")
    t = _trainer("fedavg")
    server, clients = t.init_state(jax.random.key(3))
    carry_shape = tuple(np.shape(t.model.init_carry(t.batch_size)))
    for leaf in jax.tree.leaves(clients):
        # no per-client leaf is carry-shaped (would mean a stored hidden)
        assert tuple(leaf.shape)[1:] != carry_shape, leaf.shape
    # round execution preserves that structure
    server, clients2, _ = t.run_round(server, clients)
    _, fresh = t.init_state(jax.random.key(3))
    assert jax.tree.structure(clients2) == jax.tree.structure(fresh)
