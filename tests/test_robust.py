"""Robust architectures: training-time noise ascent and adversarial
evaluation (eval.py:59-68 parity)."""
import numpy as np
import jax
import jax.numpy as jnp

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig, OptimConfig,
    TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer, evaluate
from fedtorch_tpu.parallel.evaluate import robust_noise_ascent


def _setup():
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20,
                        batch_size=16),
        federated=FederatedConfig(federated=True, num_clients=4,
                                  num_comms=5, online_client_rate=1.0,
                                  algorithm="fedavg",
                                  sync_type="local_step"),
        model=ModelConfig(arch="robust_logistic_regression"),
        optim=OptimConfig(lr=0.2, weight_decay=0.0),
        train=TrainConfig(local_step=4),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=16)
    return cfg, data, model


def test_training_does_noise_ascent():
    cfg, data, model = _setup()
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)
    server, clients = trainer.init_state(jax.random.key(0))
    noise0 = np.asarray(server.params["noise"])
    for _ in range(3):
        server, clients, _ = trainer.run_round(server, clients)
    noise1 = np.asarray(server.params["noise"])
    assert not np.allclose(noise0, noise1)  # noise moved (ascent)
    # training still converges despite the adversary
    res = evaluate(model, server.params, data.test_x, data.test_y,
                   robust_ascent=False)
    assert float(res.top1) > 0.5


def test_eval_ascent_increases_loss_and_projects():
    cfg, data, model = _setup()
    # train a few rounds first so the loss is noise-sensitive
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)
    server, clients = trainer.init_state(jax.random.key(1))
    for _ in range(3):
        server, clients, _ = trainer.run_round(server, clients)
    params = server.params

    clean = evaluate(model, params, data.test_x, data.test_y,
                     robust_ascent=False)
    adv_params = robust_noise_ascent(model, params, data.test_x,
                                     data.test_y)
    adv = evaluate(model, adv_params, data.test_x, data.test_y,
                   robust_ascent=False)
    # adversarial noise must not decrease the loss
    assert float(adv.loss) >= float(clean.loss) - 1e-5
    # and stays within the unit ball (eval.py:66-68)
    assert float(jnp.linalg.norm(adv_params["noise"])) <= 1.0 + 1e-5


def test_evaluate_applies_ascent_by_default():
    cfg, data, model = _setup()
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)
    server, clients = trainer.init_state(jax.random.key(2))
    for _ in range(2):
        server, clients, _ = trainer.run_round(server, clients)
    res_adv = evaluate(model, server.params, data.test_x, data.test_y)
    res_clean = evaluate(model, server.params, data.test_x, data.test_y,
                         robust_ascent=False)
    assert float(res_adv.loss) >= float(res_clean.loss) - 1e-5
