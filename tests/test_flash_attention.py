"""Flash-attention kernel (ops/pallas/flash_attention.py): interpret-mode
kernel semantics, custom-VJP gradients, and transformer integration.

The real-TPU lowering of the same kernel is exercised by
scripts/pallas_tpu_check.py (relay-gated)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtorch_tpu.models.transformer import TransformerLM
from fedtorch_tpu.ops.pallas.flash_attention import flash_attention
from fedtorch_tpu.parallel.sequence import reference_attention


def _qkv(B=2, T=256, H=4, D=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype) for k in ks)


class TestForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_interpret_kernel_matches_oracle(self, causal):
        """The pallas kernel (interpreter) == dense attention; T=256
        with 128-blocks exercises the multi-block online-softmax path
        and, for causal, the block-skipping loop bound."""
        q, k, v = _qkv()
        ref = reference_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, force="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_single_block_small_seq(self):
        """T smaller than the block size clamps to one block."""
        q, k, v = _qkv(T=32, D=16)
        ref = reference_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, force="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_uneven_block_ratio(self):
        """block_q != block_k exercises the inner K loop bound."""
        q, k, v = _qkv(T=256)
        ref = reference_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=128,
                              block_k=64, force="interpret")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("T", [100, 200, 257])
    def test_indivisible_seq(self, T):
        """T > block with T % block != 0 re-derives a divisor block
        (gcd, or one block for degenerate divisors) — forward AND
        gradient must both work on such shapes (T=200 -> blocks of 8;
        T=257 prime -> a single block)."""
        q, k, v = _qkv(T=T, D=32)
        ref = reference_attention(q, k, v, causal=True)
        for force in ("xla", "interpret"):
            out = flash_attention(q, k, v, causal=True, force=force)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5, rtol=2e-5,
                                       err_msg=f"force={force}")
        gf = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, causal=True, force="xla") ** 2))(q)
        gr = jax.grad(lambda q: jnp.sum(reference_attention(
            q, k, v, causal=True) ** 2))(q)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-5, rtol=5e-4)

    def test_bfloat16_inputs(self):
        q, k, v = _qkv(dtype=jnp.bfloat16)
        ref = reference_attention(q.astype(jnp.float32),
                                  k.astype(jnp.float32),
                                  v.astype(jnp.float32), causal=True)
        out = flash_attention(q, k, v, causal=True, force="interpret")
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref), atol=3e-2, rtol=3e-2)


class TestBackendSelection:
    def test_unknown_force_raises(self):
        q, k, v = _qkv(T=32, D=16)
        with pytest.raises(ValueError, match="force"):
            flash_attention(q, k, v, force="interp")  # typo'd string

    def test_mismatched_kv_length_raises_clearly(self):
        """kv_len != q_len is unsupported (shared-T tiling); it must
        fail with the shapes spelled out, not an opaque reshape error
        (ADVICE r3). Same check on the lse variant."""
        from fedtorch_tpu.ops.pallas.flash_attention import \
            flash_attention_with_lse
        q, _, _ = _qkv(T=64, D=16)
        k, _, _ = _qkv(T=32, D=16, seed=1)
        with pytest.raises(ValueError, match="identical shape"):
            flash_attention(q, k, k, force="xla")
        with pytest.raises(ValueError, match="identical shape"):
            flash_attention_with_lse(q, k, k, force="xla")

    @pytest.mark.parametrize("T,block", [(256, 128), (64, 128),
                                         (192, 128)])
    @pytest.mark.parametrize("causal", [False, True])
    def test_mosaic_lowering_accepts_blocks(self, T, block, causal):
        """AOT-lower the REAL pallas path for platform 'tpu' from this
        CPU process: jax runs Mosaic's block-mapping validation
        (_check_block_mappings) at lowering time, no device needed.
        Round 5 on-chip found that interpret mode accepts block shapes
        Mosaic rejects (the [1, block_q] lse block); this pins the
        whole failure class without a chip. Covers the clean 128-tile,
        the one-block (block == T) path, and a gcd divisor (T=192 ->
        block 64)."""
        import fedtorch_tpu.ops.pallas.flash_attention as fa
        q, k, v = _qkv(T=T, D=64)

        def fwd(q, k, v):
            (q3, k3, v3), _, scale, bq, bk, _ = fa._prep(
                q, k, v, None, block, block, None)
            o3 = fa._flash3(q3, k3, v3, scale, causal, bq, bk, True)
            _, lse3 = fa._flash3_lse(q3, k3, v3, scale, causal, bq, bk,
                                     True)
            return o3, lse3

        jax.jit(fwd).trace(q, k, v).lower(lowering_platforms=("tpu",))

    def test_default_blocks_follow_measured_winners(self):
        """Block defaults, settled per ADVICE r5: the TRAINING A/B
        (FLASH_TRAIN.json) regressed 0.68x at T=2048 on the sweep-
        derived (256, 512), so T<=2048 keeps the previously-validated
        (128, 128); the forward sweep's (512, 512) stands at T>=4096.
        Explicit args override; divisor adjustment still applies."""
        import fedtorch_tpu.ops.pallas.flash_attention as fa

        assert fa._default_blocks(1024) == (128, 128)
        assert fa._default_blocks(2048) == (128, 128)  # 0.68x window
        assert fa._default_blocks(4096) == (512, 512)
        assert fa._default_blocks(8192) == (512, 512)

        q, k, v = _qkv(T=256, D=16)
        *_, bq, bk, _ = fa._prep(q, k, v, None, None, None, None)
        assert (bq, bk) == (128, 128)  # the validated sub-2048 shape
        *_, bq, bk, _ = fa._prep(q, k, v, None, 64, 64, None)
        assert (bq, bk) == (64, 64)    # explicit args respected
        q, k, v = _qkv(T=96, D=16)     # T below the default block
        *_, bq, bk, _ = fa._prep(q, k, v, None, None, None, None)
        assert (bq, bk) == (96, 96)    # clamped to one block

    def test_lse_output_is_lane_narrow(self):
        """ADVICE r5 satellite: the lse HBM output is [BH, T, 8]
        (_LSE_LANES), not the 128-lane broadcast — 16x less lse HBM
        traffic. The narrowed write must still carry the exact lse:
        interpret-mode kernel lse == dense-oracle lse, and the full
        forward stays exact. (The Mosaic acceptance of the
        (1, block_q, 8) block is pinned by
        test_mosaic_lowering_accepts_blocks, which AOT-lowers the lse
        variant for platform 'tpu'.)"""
        import fedtorch_tpu.ops.pallas.flash_attention as fa
        from fedtorch_tpu.ops.pallas.flash_attention import \
            flash_attention_with_lse

        assert fa._LSE_LANES == 8
        # the narrow block satisfies the stated Mosaic rule by
        # construction: last block dim == array dim
        q, k, v = _qkv(T=256, D=32)
        o_i, lse_i = flash_attention_with_lse(q, k, v, causal=True,
                                              force="interpret")
        o_x, lse_x = flash_attention_with_lse(q, k, v, causal=True,
                                              force="xla")
        np.testing.assert_allclose(np.asarray(lse_i),
                                   np.asarray(lse_x),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(np.asarray(o_i), np.asarray(o_x),
                                   atol=2e-5, rtol=2e-5)

    def test_lse_kernel_shape_is_narrow(self):
        """The pallas forward's raw lse buffer really is 8 lanes (the
        HBM allocation the advisor sized), independent of the wrapper
        slicing."""
        import fedtorch_tpu.ops.pallas.flash_attention as fa

        def fwd(q3, k3, v3):
            return fa._fwd_pallas(q3, k3, v3, 0.125, False, 64, 64,
                                  interpret=True)

        shapes = jax.eval_shape(
            fwd, *(jax.ShapeDtypeStruct((4, 128, 32), jnp.float32)
                   for _ in range(3)))
        o_shape, lse_shape = shapes
        assert o_shape.shape == (4, 128, 32)
        assert lse_shape.shape == (4, 128)  # sliced from [*, *, 8]

    def test_degenerate_block_falls_back_to_xla(self, monkeypatch):
        """A prime-ish T collapses the divisor blocks to ~T; on TPU the
        [T, T] score tile would blow VMEM, so _prep must route the call
        to the XLA oracle even when the platform offers pallas."""
        import fedtorch_tpu.ops.pallas.flash_attention as fa
        monkeypatch.setattr(fa, "on_tpu", lambda: True)
        q, k, v = _qkv(T=1000, D=16)  # gcd(1000,128)=8<16 -> block=1000
        *_, use_pallas = fa._prep(q, k, v, None, 128, 128, None)
        assert use_pallas is False
        q, k, v = _qkv(T=256, D=16)   # clean tiling stays on the kernel
        *_, use_pallas = fa._prep(q, k, v, None, 128, 128, None)
        assert use_pallas is True


class TestGradients:
    @pytest.mark.parametrize("causal", [False, True])
    def test_custom_vjp_matches_dense_grads(self, causal):
        """The chunked flash backward (recompute-from-logsumexp scan)
        must reproduce the dense oracle's q/k/v gradients."""
        q, k, v = _qkv(T=128, D=32)

        def loss_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=causal, force="xla",
                                block_q=64) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(
                reference_attention(q, k, v, causal=causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-4,
                err_msg=f"d{name} mismatch")

    def test_interpret_forward_backward(self):
        """Gradients flow through the interpreter-run kernel too (the
        VJP is backend-independent)."""
        q, k, v = _qkv(T=128, D=32)
        g = jax.grad(lambda q: jnp.sum(
            flash_attention(q, k, v, causal=True,
                            force="interpret") ** 2))(q)
        assert bool(jnp.all(jnp.isfinite(g)))


class TestTransformerIntegration:
    def test_flash_model_matches_dense_model(self):
        """attention='flash' is a pure backend swap: same params, same
        logits as attention='dense'."""
        toks = jax.random.randint(jax.random.key(1), (2, 64), 0, 32)
        dense_m = TransformerLM(vocab_size=32, d_model=32, num_heads=2,
                                num_layers=2, max_len=64)
        flash_m = TransformerLM(vocab_size=32, d_model=32, num_heads=2,
                                num_layers=2, max_len=64,
                                attention="flash")
        params = dense_m.init(jax.random.key(0), toks)["params"]
        ref = dense_m.apply({"params": params}, toks)
        out = flash_m.apply({"params": params}, toks)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_flash_training_step(self):
        """End-to-end grad through the flash transformer is finite and
        matches the dense transformer's grad."""
        toks = jax.random.randint(jax.random.key(1), (2, 64), 0, 32)
        tgts = jnp.roll(toks, -1, axis=1)

        def make_loss(attention):
            m = TransformerLM(vocab_size=32, d_model=32, num_heads=2,
                              num_layers=1, max_len=64,
                              attention=attention)

            def loss(p):
                logits = m.apply({"params": p}, toks)
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(jnp.take_along_axis(
                    logp, tgts[..., None], axis=-1))

            return m, loss

        dense_m, dense_loss = make_loss("dense")
        _, flash_loss = make_loss("flash")
        params = dense_m.init(jax.random.key(0), toks)["params"]
        gd = jax.grad(dense_loss)(params)
        gf = jax.grad(flash_loss)(params)
        err = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(
            jax.tree.leaves(gd), jax.tree.leaves(gf)))
        assert err < 5e-5

    def test_config_surface(self):
        from fedtorch_tpu.config import ExperimentConfig, ModelConfig
        from fedtorch_tpu.models import define_model
        cfg = ExperimentConfig(
            model=ModelConfig(arch="transformer", attention="flash",
                              mlp_num_layers=1, rnn_seq_len=16,
                              rnn_hidden_size=8)).finalize()
        model = define_model(cfg, batch_size=2)
        assert model.module.attention == "flash"


class TestAutoDispatch:
    """Sequence-length dispatch guard (ISSUE 3 satellite): 'auto' must
    keep the measured T=2048 regression window (FLASH_TRAIN.json read
    flash at 0.68x dense there) off the flash kernel, and flip to
    flash exactly where the on-chip A/B measured the win."""

    def test_boundary(self):
        from fedtorch_tpu.ops.attention_dispatch import (
            FLASH_MIN_SEQ_LEN, resolve_attention,
        )
        assert resolve_attention("auto", 1024) == "dense"
        assert resolve_attention("auto", 2048) == "dense"  # 0.68x case
        assert resolve_attention("auto", FLASH_MIN_SEQ_LEN - 1) \
            == "dense"
        assert resolve_attention("auto", FLASH_MIN_SEQ_LEN) == "flash"
        assert resolve_attention("auto", 8192) == "flash"

    def test_explicit_modes_pass_through(self):
        from fedtorch_tpu.ops.attention_dispatch import (
            resolve_attention,
        )
        assert resolve_attention("dense", 8192) == "dense"
        assert resolve_attention("flash", 128) == "flash"
        with pytest.raises(ValueError, match="attention"):
            resolve_attention("fast", 128)

    def test_auto_is_the_config_default(self):
        from fedtorch_tpu.config import ExperimentConfig, ModelConfig
        assert ExperimentConfig().finalize().model.attention == "auto"
        with pytest.raises(ValueError, match="attention"):
            ExperimentConfig(
                model=ModelConfig(attention="fast")).finalize()

    def test_auto_equals_dense_below_threshold(self):
        """At short T the 'auto' model must be the dense model
        bit-for-bit (same params, same logits)."""
        toks = jnp.arange(2 * 16, dtype=jnp.int32).reshape(2, 16) % 7
        outs = {}
        for mode in ("auto", "dense"):
            m = TransformerLM(vocab_size=7, d_model=16, num_heads=2,
                              num_layers=1, attention=mode)
            params = m.init(jax.random.key(0), toks)["params"]
            outs[mode] = m.apply({"params": params}, toks)
        np.testing.assert_array_equal(np.asarray(outs["auto"]),
                                      np.asarray(outs["dense"]))
