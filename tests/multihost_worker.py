"""Worker process for the multi-host smoke test (test_multihost.py).

Each of the two processes owns 4 virtual CPU devices; together they form
an 8-device global mesh over which one federated round executes — the
DCN analog of the reference's ``dist.init_process_group('mpi')`` bring-up
(main.py:17). Bring-up shared with the 4-process interrupt-resume
scenario via mh_common.py. Run as:

    python tests/multihost_worker.py <port> <process_id> [ckpt_dir]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from mh_common import bringup, configure_env  # noqa: E402

port, pid = sys.argv[1], int(sys.argv[2])
configure_env(local_devices=4)  # before the first jax import

jax, cfg, trainer = bringup(port, pid, num_processes=2,
                            local_devices=4, online_client_rate=1.0)
assert len(jax.devices()) == 8, jax.devices()
assert trainer.padded_clients == 16  # 10 clients padded over 8 devices

server, clients = trainer.init_state(jax.random.key(0))
leaf = jax.tree.leaves(clients.params)[0]
assert len(leaf.sharding.device_set) == 8, leaf.sharding

for _ in range(2):
    server, clients, metrics = trainer.run_round(server, clients)
jax.block_until_ready(server.params)

# checkpoint across hosts: the snapshot is a COLLECTIVE (client state
# is sharded across the two processes and must be allgathered); only
# process 0 writes. Both processes MUST make the call.
ckpt_dir = sys.argv[3] if len(sys.argv) > 3 else None
if ckpt_dir:
    from jax.experimental import multihost_utils
    from fedtorch_tpu.utils import maybe_resume, save_checkpoint
    save_checkpoint(ckpt_dir, server, clients, cfg, best_prec1=0.25,
                    is_best=False)
    if pid == 0:
        assert os.path.exists(os.path.join(ckpt_dir, "checkpoint.ckpt"))
    # barrier: process 1 must not read before process 0's write lands
    multihost_utils.sync_global_devices("checkpoint-written")
    # resume restores the sharded state on BOTH processes
    s2, c2 = trainer.init_state(jax.random.key(1))
    s2, c2, best, resumed = maybe_resume(ckpt_dir, s2, c2, cfg, None)
    assert resumed and best == 0.25 and int(s2.round) == 2
    server2, clients2, m2 = trainer.run_round(s2, c2)
    jax.block_until_ready(server2.params)
    print(f"MULTIHOST_CKPT_OK pid={pid}", flush=True)

# replicated scalars are fetchable on every host
loss = float(metrics.train_loss.sum()) / 10.0
epoch = trainer.mean_client_epoch(clients)
assert loss == loss and epoch > 0, (loss, epoch)
print(f"MULTIHOST_OK pid={pid} loss={loss:.6f} epoch={epoch:.3f}",
      flush=True)
jax.distributed.shutdown()
