"""Worker process for the multi-host smoke test (test_multihost.py).

Each of the two processes owns 4 virtual CPU devices; together they form
an 8-device global mesh over which one federated round executes — the
DCN analog of the reference's ``dist.init_process_group('mpi')`` bring-up
(main.py:17). Run as:

    python tests/multihost_worker.py <port> <process_id>
"""
import os
import sys

port, pid = sys.argv[1], int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # keep sitecustomize off TPU

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from fedtorch_tpu.algorithms import make_algorithm  # noqa: E402
from fedtorch_tpu.config import (  # noqa: E402
    DataConfig, ExperimentConfig, FederatedConfig, MeshConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data  # noqa: E402
from fedtorch_tpu.models import define_model  # noqa: E402
from fedtorch_tpu.parallel import FederatedTrainer, init_multihost  # noqa: E402

mesh_cfg = MeshConfig(coordinator_address=f"localhost:{port}",
                      num_processes=2, process_id=pid)
init_multihost(mesh_cfg)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

cfg = ExperimentConfig(
    data=DataConfig(dataset="synthetic", synthetic_dim=12, batch_size=8),
    federated=FederatedConfig(federated=True, num_clients=10,
                              online_client_rate=1.0, algorithm="fedavg",
                              sync_type="local_step"),
    model=ModelConfig(arch="logistic_regression"),
    optim=OptimConfig(lr=0.1, weight_decay=0.0),
    train=TrainConfig(local_step=2),
    mesh=mesh_cfg,
).finalize()
# every process derives identical data/partitions from the shared seed —
# the determinism contract that replaces the reference's rank-0 broadcast
# (partition.py:25-33; docs/multihost.md 'Determinism across hosts')
data = build_federated_data(cfg)
model = define_model(cfg, batch_size=cfg.data.batch_size)
trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)
assert trainer.mesh.devices.size == 8
assert trainer.padded_clients == 16  # 10 clients padded over 8 devices

server, clients = trainer.init_state(jax.random.key(0))
leaf = jax.tree.leaves(clients.params)[0]
assert len(leaf.sharding.device_set) == 8, leaf.sharding

for _ in range(2):
    server, clients, metrics = trainer.run_round(server, clients)
jax.block_until_ready(server.params)

# checkpoint across hosts: the snapshot is a COLLECTIVE (client state
# is sharded across the two processes and must be allgathered); only
# process 0 writes. Both processes MUST make the call.
ckpt_dir = sys.argv[3] if len(sys.argv) > 3 else None
if ckpt_dir:
    from jax.experimental import multihost_utils
    from fedtorch_tpu.utils import maybe_resume, save_checkpoint
    save_checkpoint(ckpt_dir, server, clients, cfg, best_prec1=0.25,
                    is_best=False)
    if pid == 0:
        assert os.path.exists(os.path.join(ckpt_dir, "checkpoint.ckpt"))
    # barrier: process 1 must not read before process 0's write lands
    multihost_utils.sync_global_devices("checkpoint-written")
    # resume restores the sharded state on BOTH processes
    s2, c2 = trainer.init_state(jax.random.key(1))
    s2, c2, best, resumed = maybe_resume(ckpt_dir, s2, c2, cfg, None)
    assert resumed and best == 0.25 and int(s2.round) == 2
    server2, clients2, m2 = trainer.run_round(s2, c2)
    jax.block_until_ready(server2.params)
    print(f"MULTIHOST_CKPT_OK pid={pid}", flush=True)

# replicated scalars are fetchable on every host
loss = float(metrics.train_loss.sum()) / 10.0
epoch = trainer.mean_client_epoch(clients)
assert loss == loss and epoch > 0, (loss, epoch)
print(f"MULTIHOST_OK pid={pid} loss={loss:.6f} epoch={epoch:.3f}",
      flush=True)
jax.distributed.shutdown()
