"""Slow-lane smoke for the async commit-throughput A/B
(scripts/async_bench.py → ASYNC_AB.json): the capture must run end to
end on the CPU mesh, prove the commit clock is not gated on the tail,
stay retrace-free in the timed window, and emit a well-formed record —
so the on-chip capture (tpu_capture.sh `async` step) cannot be the
first time the script ever executes."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_async_bench_smoke(tmp_path):
    out_path = str(tmp_path / "ASYNC_AB.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ASYNC_BENCH_SMOKE="1", ASYNC_AB_PATH=out_path)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "async_bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(out_path) as f:
        report = json.load(f)
    assert set(report["modes"]) == {"sync", "async"}
    for mode in report["modes"].values():
        assert mode["retraces_during_timed"] == 0
        assert mode["virtual_time_total"] > 0
    # the headline: the commit clock beats the straggler-set round
    # clock under the same delay model
    assert report["async_not_tail_gated"] is True
    assert report["commit_rate_speedup_virtual"] > 1.0
    a = report["modes"]["async"]
    assert a["staleness_mean"] > 0
    assert a["scheduler"]["stragglers"] > 0
