"""The million-client data plane (ISSUE 18): the ClientStore seam
(zero-copy RAM store, manifest-described mmap store, chunked writer),
O(k) 'sparse' participation (device draw + host RoundSchedule replay +
async event scheduler), config/CLI surface for the new knobs, and the
population-scaling bench smoke (scripts/stream_bench.py population arm
→ MILLION_CLIENT_AB.json)."""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.async_plane.scheduler import AsyncSchedule
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.data.batching import ClientData
from fedtorch_tpu.data.streaming import (
    MANIFEST_NAME, HostClientStore, MmapClientStore, MmapStoreWriter,
    save_client_store,
)
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.parallel.federated import participation_indices
from fedtorch_tpu.robustness import HostSeamError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_cfg(plane="stream", store="ram", store_dir="",
             participation_mode="perm", num_clients=8, online_rate=0.5):
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20,
                        batch_size=16, synthetic_alpha=0.5,
                        synthetic_beta=0.5, data_plane=plane,
                        store=store, store_dir=store_dir),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients,
            online_client_rate=online_rate, algorithm="fedavg",
            sync_type="local_step",
            participation_mode=participation_mode),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.3, weight_decay=0.0),
        train=TrainConfig(local_step=3),
    ).finalize()


def build(cfg, data):
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)


def _toy_population(C=6, n_max=10, F=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(C, n_max, F).astype(np.float32)
    y = rng.randint(0, 10, (C, n_max)).astype(np.int32)
    sizes = rng.randint(0, n_max + 1, C).astype(np.int32)
    sizes[0], sizes[1] = n_max, 0  # a full shard and an empty client
    return ClientData(x=x, y=y, sizes=sizes)


def assert_feeds_equal(a, b):
    for la, lb in zip(a, b):
        assert (la is None) == (lb is None)
        if la is not None:
            np.testing.assert_array_equal(np.asarray(la),
                                          np.asarray(lb))


# -- the RAM store: zero-copy + the int32-overflow fallback ------------------
def test_host_store_zero_copy_when_contiguous():
    """The no-silent-duplication contract: contiguous host inputs are
    ALIASED, not copied — at million-client scale an accidental copy
    doubles peak host RAM."""
    data = _toy_population()
    store = HostClientStore(data)
    assert np.shares_memory(store.x, data.x)
    assert np.shares_memory(store.y, data.y)
    assert np.shares_memory(store.sizes, data.sizes)
    # a non-contiguous input pays exactly one materialization
    sliced = HostClientStore(ClientData(
        x=data.x[:, ::2], y=data.y[:, ::2], sizes=data.sizes))
    assert not np.shares_memory(sliced.x, data.x)
    assert sliced.x.flags.c_contiguous


def test_int32_overflow_fallback_bitwise():
    """Stores past 2^31-1 total rows clear ``_native_ok`` and gather
    via numpy fancy indexing; forcing the flag off must not change a
    single byte of ``pack`` or ``pack_window`` output (including the
    clamped ``pre_round`` columns when batch_size > n_max)."""
    store = HostClientStore(_toy_population())
    assert store._native_ok
    idx = np.asarray([1, 3, 0], np.int64)
    rows = np.random.RandomState(2).randint(
        0, store.n_max, (3, 5)).astype(np.int64)
    over = store.n_max + 3  # forces the pre-column clamp
    native = store.pack(idx, rows, batch_size=over)
    idxs = np.asarray([[0, 1], [2, 3]], np.int64)
    rowss = np.random.RandomState(3).randint(
        0, store.n_max, (2, 2, 4)).astype(np.int64)
    native_w = store.pack_window(idxs, rowss, batch_size=over)

    store._native_ok = False  # what a past-2^31-rows store sets
    assert_feeds_equal(store.pack(idx, rows, batch_size=over), native)
    assert_feeds_equal(store.pack_window(idxs, rowss, batch_size=over),
                       native_w)


# -- the mmap store: round-trip + feed parity --------------------------------
@pytest.mark.parametrize("cps,chunk", [(2, 2), (3, 2), (64, 4096)])
def test_mmap_store_matches_ram_store_bitwise(tmp_path, cps, chunk):
    """Same schedule => identical RoundFeed bytes from the disk-backed
    store and the RAM store, across shard-straddling chunked writes
    (cps=2/3) and the single-shard layout (cps=64). Residency splits
    as documented: the mmap store pins only the sizes vector."""
    data = _toy_population()
    ram = HostClientStore(data)
    save_client_store(str(tmp_path), data, clients_per_shard=cps,
                      chunk_clients=chunk)
    mm = MmapClientStore(str(tmp_path))
    assert (mm.num_clients, mm.n_max) == (ram.num_clients, ram.n_max)
    np.testing.assert_array_equal(mm.sizes, ram.sizes)

    idx = np.asarray([5, 1, 0, 3], np.int64)
    rows = np.random.RandomState(1).randint(
        0, mm.n_max, (4, 6)).astype(np.int64)
    assert_feeds_equal(mm.pack(idx, rows, 4), ram.pack(idx, rows, 4))
    assert_feeds_equal(mm.pack_shards(idx, 4), ram.pack_shards(idx, 4))
    idxs, rowss = idx.reshape(2, 2), rows.reshape(2, 2, 6)
    assert_feeds_equal(mm.pack_window(idxs, rowss, 4),
                       ram.pack_window(idxs, rowss, 4))
    for a, b in zip(mm.pack_probe(idx[:2], rows[:2, :3]),
                    ram.pack_probe(idx[:2], rows[:2, :3])):
        np.testing.assert_array_equal(a, b)

    # residency: RAM store holds the arrays; mmap store maps them
    assert ram.resident_nbytes == data.x.nbytes + data.y.nbytes
    assert ram.mapped_nbytes == 0
    assert mm.resident_nbytes == mm.sizes.nbytes
    assert mm.mapped_nbytes == data.x.nbytes + data.y.nbytes


def test_mmap_as_client_data_is_zero_ram_view(tmp_path):
    """The trainer-construction view: real sizes, stride-0 broadcast
    stubs for x/y (shape/dtype metadata only — never O(C) RAM)."""
    data = _toy_population()
    save_client_store(str(tmp_path), data)
    view = MmapClientStore(str(tmp_path)).as_client_data()
    assert view.x.shape == data.x.shape
    assert view.x.dtype == data.x.dtype
    assert view.y.shape == data.y.shape
    assert view.x.strides == (0,) * view.x.ndim
    np.testing.assert_array_equal(view.sizes, data.sizes)


def test_store_manifest_validation(tmp_path):
    with pytest.raises(ValueError, match="save_client_store"):
        MmapClientStore(str(tmp_path))  # no manifest yet

    data = _toy_population()
    mpath = save_client_store(str(tmp_path), data, clients_per_shard=2)
    man = json.loads(mpath.read_text())

    def rewrite(**kw):
        mpath.write_text(json.dumps({**man, **kw}))

    rewrite(format="not-a-store")
    with pytest.raises(ValueError, match="format"):
        MmapClientStore(str(tmp_path))
    rewrite(version=99)
    with pytest.raises(ValueError, match="version"):
        MmapClientStore(str(tmp_path))
    # per-shard gather must stay int32-legal by construction
    rewrite(clients_per_shard=2 ** 28, n_max=2 ** 10)
    with pytest.raises(ValueError, match="int32"):
        MmapClientStore(str(tmp_path))
    # shard list out of step with the layout
    bad = json.loads(json.dumps(man))
    bad["tensors"]["x"]["shards"] = bad["tensors"]["x"]["shards"][:-1]
    mpath.write_text(json.dumps(bad))
    with pytest.raises(ValueError, match="shards"):
        MmapClientStore(str(tmp_path))
    # sizes file out of step with num_clients
    mpath.write_text(json.dumps(man))
    sizes_path = tmp_path / man["sizes_file"]
    sizes_path.write_bytes(sizes_path.read_bytes()[:-4])
    with pytest.raises(ValueError, match="sizes"):
        MmapClientStore(str(tmp_path))


def test_store_writer_guards():
    with pytest.raises(ValueError, match="int32"):
        MmapStoreWriter("/tmp/unused", n_max=2 ** 20,
                        x_feat=(1,), y_feat=(), x_dtype=np.float32,
                        y_dtype=np.int32, clients_per_shard=2 ** 12)


def test_store_writer_rejects_mismatched_chunks(tmp_path):
    w = MmapStoreWriter(str(tmp_path), n_max=4, x_feat=(2,), y_feat=(),
                        x_dtype=np.float32, y_dtype=np.int32)
    with pytest.raises(ValueError, match="chunk shapes"):
        w.append(np.zeros((3, 4, 2), np.float32),
                 np.zeros((3, 5), np.int32), np.zeros((3,), np.int32))


# -- the mmap store through the trainer --------------------------------------
def test_mmap_trainer_matches_ram_trainer_bitwise(tmp_path):
    """data.store='mmap' vs the default RAM store: BITWISE-identical
    trajectories — the store seam changes residency, never bytes."""
    cfg_ram = make_cfg()
    data = build_federated_data(cfg_ram)
    save_client_store(str(tmp_path), data.train, clients_per_shard=3)
    cfg_mm = make_cfg(store="mmap", store_dir=str(tmp_path))
    t_ram, t_mm = build(cfg_ram, data), build(cfg_mm, data)
    assert t_mm.host_store.resident_nbytes \
        < t_ram.host_store.resident_nbytes
    s1, c1 = t_ram.init_state(jax.random.key(0))
    s2, c2 = t_mm.init_state(jax.random.key(0))
    for _ in range(3):
        s1, c1, m1 = t_ram.run_round(s1, c1)
        s2, c2, m2 = t_mm.run_round(s2, c2)
    for la, lb in zip(jax.tree.leaves((s1.params, s1.aux, c1, m1)),
                      jax.tree.leaves((s2.params, s2.aux, c2, m2))):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    t_ram.invalidate_stream()
    t_mm.invalidate_stream()


def test_torn_shard_raises_named_seam_error(tmp_path):
    """A truncated shard file must surface as a NAMED HostSeamError
    chain — the mmap length check fails the 'stream.gather' bounded
    retry, the trainer's producer-rebuild layer retries against the
    same torn file and escalates as 'stream.producer' chained to the
    gather-seam exhaustion — never as a raw mmap ValueError from a
    worker thread."""
    cfg = make_cfg(store="mmap", store_dir=str(tmp_path))
    data = build_federated_data(cfg)
    save_client_store(str(tmp_path), data.train, clients_per_shard=3)
    for shard in tmp_path.glob("x.*.bin"):  # tear every x shard
        shard.write_bytes(shard.read_bytes()[:16])
    t = build(cfg, data)
    server, clients = t.init_state(jax.random.key(0))
    try:
        with pytest.raises(HostSeamError, match="stream.gather") as ei:
            for _ in range(3):
                server, clients, _ = t.run_round(server, clients)
        assert ei.value.seam == "stream.producer"
        cause = ei.value.__cause__
        assert isinstance(cause, HostSeamError)
        assert cause.seam == "stream.gather"
    finally:
        t.invalidate_stream()


def test_trainer_rejects_store_shape_mismatch(tmp_path):
    cfg = make_cfg(store="mmap", store_dir=str(tmp_path),
                   num_clients=8)
    data = build_federated_data(cfg)
    save_client_store(str(tmp_path), _toy_population(C=5))
    with pytest.raises(ValueError, match="mmap client store"):
        build(cfg, data)


# -- O(k) 'sparse' participation ---------------------------------------------
def test_sparse_draw_valid_and_forces_client0():
    key = jax.random.key(11)
    for r in (0, 1, 7):
        idx = np.asarray(participation_indices(
            jax.random.fold_in(key, r), 1000, 16, jnp.int32(r),
            mode="sparse"))
        assert len(set(idx.tolist())) == 16  # without replacement
        assert (idx >= 0).all() and (idx < 1000).all()
        if r == 0:
            assert 0 in idx  # round-0 forcing, same as 'perm'


def test_perm_mode_is_the_untouched_default():
    key = jax.random.key(5)
    legacy = participation_indices(key, 40, 8, jnp.int32(3))
    np.testing.assert_array_equal(
        np.asarray(legacy),
        np.asarray(participation_indices(key, 40, 8, jnp.int32(3),
                                         mode="perm")))
    # and it IS the legacy permutation prefix, bitwise
    np.testing.assert_array_equal(
        np.asarray(legacy),
        np.asarray(jax.random.permutation(key, 40)[:8]))


def test_sparse_stream_matches_device_bitwise():
    """participation_mode='sparse' replays bit-exactly through the
    host RoundSchedule: the stream plane's trajectory equals the
    device plane's over multiple rounds."""
    cfg_d = make_cfg(plane="device", participation_mode="sparse")
    cfg_s = make_cfg(plane="stream", participation_mode="sparse")
    data = build_federated_data(cfg_d)
    t_dev, t_str = build(cfg_d, data), build(cfg_s, data)
    s1, c1 = t_dev.init_state(jax.random.key(9))
    s2, c2 = t_str.init_state(jax.random.key(9))
    for _ in range(3):
        s1, c1, m1 = t_dev.run_round(s1, c1)
        s2, c2, m2 = t_str.run_round(s2, c2)
    for la, lb in zip(jax.tree.leaves((s1.params, s1.aux, c1, m1)),
                      jax.tree.leaves((s2.params, s2.aux, c2, m2))):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    t_str.invalidate_stream()


# -- the async event scheduler's sparse mode ---------------------------------
def _sched(start_commit=0, num_clients=16, **kw):
    key = jax.random.key(7)
    key_data = np.asarray(jax.device_get(jax.random.key_data(key)))
    return AsyncSchedule(
        key_data, jax.random.key_impl(key), num_clients=num_clients,
        concurrency=6, buffer_size=3, ring_size=8,
        start_commit=start_commit, straggler_rate=0.4,
        straggler_step_frac=0.1, **kw)


def test_async_perm_default_bitwise_unchanged():
    """participation_mode defaults to 'perm' and the explicit spelling
    is byte-identical — the legacy async stream is pinned."""
    a, b = _sched(), _sched(participation_mode="perm")
    for _ in range(5):
        pa, pb = a.next_commit(), b.next_commit()
        assert pa.commit == pb.commit
        np.testing.assert_array_equal(pa.idx, pb.idx)
        np.testing.assert_array_equal(pa.version, pb.version)
        np.testing.assert_array_equal(pa.arrival_times,
                                      pb.arrival_times)


def test_async_sparse_deterministic_and_valid():
    a, b = _sched(participation_mode="sparse"), \
        _sched(participation_mode="sparse")
    for _ in range(6):
        pa, pb = a.next_commit(), b.next_commit()
        np.testing.assert_array_equal(pa.idx, pb.idx)
        np.testing.assert_array_equal(pa.arrival_times,
                                      pb.arrival_times)
        # in-flight cohort stays distinct clients in range
        assert len(set(pa.idx.tolist())) == len(pa.idx)
        assert (pa.idx >= 0).all() and (pa.idx < 16).all()


def test_async_sparse_fast_forward_equals_stepped():
    live = _sched(participation_mode="sparse")
    for _ in range(4):
        live.next_commit()
    resumed = _sched(start_commit=4, participation_mode="sparse")
    for _ in range(3):
        pl, pr = live.next_commit(), resumed.next_commit()
        assert pl.commit == pr.commit
        np.testing.assert_array_equal(pl.idx, pr.idx)
        np.testing.assert_array_equal(pl.version, pr.version)


def test_async_rejects_unknown_mode():
    with pytest.raises(ValueError, match="participation_mode"):
        _sched(participation_mode="reservoir")


# -- config / CLI surface ----------------------------------------------------
def test_config_rejects_bad_store_knobs():
    with pytest.raises(ValueError, match="data.store"):
        make_cfg(store="redis")
    with pytest.raises(ValueError, match="stream-plane client store"):
        make_cfg(plane="device", store="mmap", store_dir="/x")
    with pytest.raises(ValueError, match="needs data.store_dir"):
        make_cfg(store="mmap")
    with pytest.raises(ValueError, match="participation_mode"):
        make_cfg(participation_mode="reservoir")


def test_cli_flags_map_to_config(tmp_path):
    from fedtorch_tpu.cli import args_to_config, build_parser
    cfg = args_to_config(build_parser().parse_args(
        ["--federated", "true", "-d", "synthetic",
         "--data_plane", "stream", "--data_store", "mmap",
         "--data_store_dir", str(tmp_path),
         "--participation_mode", "sparse"]))
    assert cfg.data.store == "mmap"
    assert cfg.data.store_dir == str(tmp_path)
    assert cfg.federated.participation_mode == "sparse"


# -- the population-scaling bench (slow lane) --------------------------------
@pytest.mark.slow
def test_population_bench_smoke(tmp_path):
    """The population arm of scripts/stream_bench.py must run end to
    end on the CPU mesh (smoke sizes), prove mmap-vs-RAM bitwise
    parity + residency split + zero retraces, and leave run dirs the
    compare tool can read — so the on-chip capture (tpu_capture.sh
    `population` step) is never its first execution."""
    out = tmp_path / "MILLION_CLIENT_AB.json"
    runs = tmp_path / "population_ab"
    env = dict(os.environ, JAX_PLATFORMS="cpu", STREAM_BENCH_SMOKE="1",
               STREAM_BENCH_POPULATION="1",
               MILLION_CLIENT_AB_PATH=str(out),
               POPULATION_RUNS_DIR=str(runs))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "stream_bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(out.read_text())
    assert report["parity_bitwise_mmap_vs_ram"] is True
    assert report["residency_mapped_not_resident"] is True
    assert report["zero_retraces"] is True
    assert len(report["populations"]) >= 2
    # the run dirs feed the gated compare (MILLION_CLIENT_COMPARE)
    cmp_out = tmp_path / "cmp.json"
    cproc = subprocess.run(
        [sys.executable, "-m", "fedtorch_tpu.tools.compare",
         str(runs / "a"), str(runs / "b"), "--out", str(cmp_out)],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert cproc.returncode == 0, cproc.stderr[-2000:]
    blob = cmp_out.read_text()
    assert "round_s_mean_steady" in blob
    assert "stream_store_mapped_mb" in blob
