"""Third-party algorithm extension API (examples/02_custom_algorithm.py).

The framework's contract with downstream algorithm authors is the
``FedAlgorithm`` hook surface (algorithms/base.py): a subclass overriding
only ``client_payload``/``server_update`` must slot into the engine's
jitted round program with no engine changes. These tests pin that
contract with the FedNova example:

* dict-shaped payloads (delta tree + scalar side-channel) survive the
  stacked-sum aggregation collective;
* ``local_steps`` passed to ``client_payload`` is the client's EFFECTIVE
  budget, so tau-normalization composes with epoch-sync masking;
* with uniform step counts FedNova reduces exactly to FedAvg (tau_i = K
  for all i -> payload*K/K), so trajectories must match bitwise-close.
"""
import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np

from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer

_EXAMPLE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples", "02_custom_algorithm.py")


def _load_fednova():
    spec = importlib.util.spec_from_file_location("example_fednova",
                                                  _EXAMPLE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.FedNova


def _trainer(algorithm_cls, sync_type="local_step"):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=16,
                        batch_size=8),
        federated=FederatedConfig(
            federated=True, num_clients=8, online_client_rate=1.0,
            algorithm="fedavg", sync_type=sync_type,
            num_epochs_per_comm=1),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.05, weight_decay=0.0),
        train=TrainConfig(local_step=4),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    return FederatedTrainer(cfg, model, algorithm_cls(cfg), data.train)


def _run(trainer, rounds=5):
    server, clients = trainer.init_state(jax.random.key(0))
    for _ in range(rounds):
        server, clients, metrics = trainer.run_round(server, clients)
    return server, metrics


def test_fednova_equals_fedavg_under_uniform_steps():
    """tau_i identical for every client -> FedNova IS FedAvg."""
    FedNova = _load_fednova()
    s_base, _ = _run(_trainer(FedAlgorithm))
    s_nova, _ = _run(_trainer(FedNova))
    for a, b in zip(jax.tree.leaves(s_base.params),
                    jax.tree.leaves(s_nova.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_fednova_trains_under_epoch_sync_skew():
    """Dict payloads + per-client tau under heterogeneous budgets: the
    round must run, produce finite loss, and actually learn."""
    FedNova = _load_fednova()
    trainer = _trainer(FedNova, sync_type="epoch")
    server, clients = trainer.init_state(jax.random.key(0))
    first = None
    for _ in range(8):
        server, clients, metrics = trainer.run_round(server, clients)
        loss = float(metrics.train_loss.sum() / metrics.online_mask.sum())
        assert np.isfinite(loss)
        first = loss if first is None else first
    assert loss < first


def test_custom_payload_hook_math():
    """client_payload/server_update compose: normalized payloads summed
    over clients, rescaled by the weighted-mean tau, reproduce the exact
    FedNova update on hand-built deltas with heterogeneous taus."""
    FedNova = _load_fednova()
    trainer = _trainer(FedNova)
    alg = trainer.algorithm
    deltas = [{"w": jnp.full((3,), float(i + 1))} for i in range(4)]
    taus = jnp.asarray([2, 4, 8, 2], jnp.int32)
    w = 0.25
    payloads, sums = [], None
    for d, t in zip(deltas, taus):
        p, _ = alg.client_payload(
            delta=d, client_aux=(), params=None, server_params=None,
            server_aux=(), lr=0.1, local_steps=t, weight=jnp.asarray(w))
        payloads.append(p)
    sums = jax.tree.map(lambda *xs: sum(xs), *payloads)
    # wtau = sum w*tau = 0.25*(2+4+8+2) = 4; normalized delta sum =
    # 0.25*(1/2 + 2/4 + 3/8 + 4/2) = 0.25*3.375
    np.testing.assert_allclose(float(sums["wtau"]), 4.0, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(sums["delta"]["w"]),
        np.full(3, 0.25 * (1 / 2 + 2 / 4 + 3 / 8 + 4 / 2)), rtol=1e-6)
    # the server applies wtau * delta_sum through the dual-mode step
    update = jax.tree.map(lambda x: x * sums["wtau"], sums["delta"])
    np.testing.assert_allclose(
        np.asarray(update["w"]),
        np.asarray(sums["delta"]["w"]) * 4.0, rtol=1e-6)
