"""Distributed local-SGD mode tests."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig, OptimConfig,
    TrainConfig,
)
from fedtorch_tpu.data import generate_synthetic
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import build_local_sgd, evaluate


def _setup(num_epochs=3, local_step=4, avg_model=True, **train_kw):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=16,
                        batch_size=20),
        federated=FederatedConfig(federated=False, num_clients=8),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.3, weight_decay=0.0),
        train=TrainConfig(num_epochs=num_epochs, local_step=local_step,
                          avg_model=avg_model, **train_kw),
    ).finalize()
    d = generate_synthetic(num_tasks=4, alpha=0.0, beta=0.0, num_dim=16)
    feats = np.concatenate(d.client_x)
    labels = np.concatenate(d.client_y)
    model = define_model(cfg, batch_size=20)
    trainer = build_local_sgd(cfg, model, feats, labels)
    return trainer, (d.test_x, d.test_y)


def test_runs_and_converges():
    trainer, (tx, ty) = _setup(num_epochs=3, local_step=4)
    server, clients, history = trainer.fit(jax.random.key(0))
    assert len(history) > 0
    res = evaluate(trainer.model, server.params, tx, ty, batch_size=128)
    first = float(jnp.sum(history[0].train_loss) / 8)
    last = float(jnp.sum(history[-1].train_loss) / 8)
    assert last < first
    assert float(res.top1) > 0.6


def test_all_workers_online_every_round():
    trainer, _ = _setup(num_epochs=1)
    _, _, history = trainer.fit(jax.random.key(1))
    for m in history:
        assert float(jnp.sum(m.online_mask)) == 8.0


def test_iteration_stop_criterion():
    trainer, _ = _setup(num_epochs=100, local_step=2,
                        stop_criteria="iteration", num_iterations=6)
    server, clients, history = trainer.fit(jax.random.key(2))
    assert int(jnp.max(clients.local_index)) >= 6
    assert len(history) == 3  # 6 iterations / 2 per round


def test_warmup_schedule_varies_round_length():
    trainer, _ = _setup(num_epochs=3, local_step=4,
                        local_step_warmup_type="linear",
                        local_step_warmup_period=2)
    # schedule: epoch0 -> 2 steps, epoch1+ -> 4 steps
    assert trainer.steps_schedule[0] == 2
    assert trainer.steps_schedule[2] == 4
    server, clients, history = trainer.fit(jax.random.key(3))
    assert len(trainer._round_cache) >= 2  # two distinct K compiled


def test_growing_batch_mode():
    """Growing minibatch (dataset.py:276-317): batch size grows over the
    run, bucketed to powers of two for compile caching."""
    import dataclasses
    from fedtorch_tpu.config import DataConfig
    trainer, (tx, ty) = _setup(num_epochs=2, local_step=2)
    cfg = dataclasses.replace(
        trainer.cfg, data=dataclasses.replace(
            trainer.cfg.data, growing_batch_size=True, base_batch_size=4,
            max_batch_size=64))
    from fedtorch_tpu.parallel.local_sgd import LocalSGDTrainer
    import numpy as np
    feats = np.asarray(trainer.data.x).reshape(-1, 16)
    labels = np.asarray(trainer.data.y).reshape(-1)
    from fedtorch_tpu.parallel import build_local_sgd
    from fedtorch_tpu.models import define_model
    model = define_model(cfg, batch_size=4)
    t2 = build_local_sgd(cfg, model, feats, labels)
    assert t2._batch_schedule is not None
    assert t2._batch_schedule[0] == 5  # int(4*1.01^0)+1
    server, clients, history = t2.fit(jax.random.key(5))
    assert len(history) > 0
    # rounds ran with a schedule-derived (non-None) batch bucket
    batch_keys = {k[1] for k in t2._round_cache}
    assert batch_keys and None not in batch_keys, batch_keys
    assert t2._bucketed_batch(0) == 8  # int(4*1.01^0)+1 = 5 -> pow2 8

    # longer run (more epochs -> longer per-worker schedule) crosses
    # power-of-two buckets and sustains the peak past the schedule end
    cfg40 = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, num_epochs=40))
    t3 = build_local_sgd(cfg40, define_model(cfg40, batch_size=4),
                         feats, labels)
    sched = t3._batch_schedule
    assert len(sched) > 100
    assert t3._bucketed_batch(len(sched) // 2) >= 8
    # past the end: peak (not a remainder tail batch), capped at 64
    assert t3._bucketed_batch(10_000) == \
        min(64, 1 << (max(sched) - 1).bit_length())
    # a non-power-of-two cap is never exceeded
    cfg48 = dataclasses.replace(
        cfg40, data=dataclasses.replace(cfg40.data, max_batch_size=48))
    t4 = build_local_sgd(cfg48, define_model(cfg48, batch_size=4),
                         feats, labels)
    assert all(t4._bucketed_batch(s) <= 48 for s in (0, 100, 10_000))
    # no zero entries in a capped schedule (a 0 would mean a B=1 round)
    assert min(t4._batch_schedule) >= 1


def test_sum_mode_changes_magnitude():
    t_avg, _ = _setup(avg_model=True, num_epochs=1, local_step=2)
    t_sum, _ = _setup(avg_model=False, num_epochs=1, local_step=2)
    s_a, _, _ = t_avg.fit(jax.random.key(4))
    s_s, _, _ = t_sum.fit(jax.random.key(4))
    # sum-mode updates are ~8x larger -> different params
    diff = sum(float(jnp.abs(a - b).max())
               for a, b in zip(jax.tree.leaves(s_a.params),
                               jax.tree.leaves(s_s.params)))
    assert diff > 1e-4
