"""Dataset format readers exercised against small synthetic fixtures in
the exact on-disk formats (idx, CIFAR pickle, TFF HDF5, svmlight, adult
CSV, STL10 binary) — no network needed."""
import gzip
import os
import pickle
import struct

import numpy as np
import pytest

from fedtorch_tpu.config import DataConfig
from fedtorch_tpu.data.datasets import (
    get_dataset, load_adult, load_cifar, load_emnist, load_libsvm,
    load_mnist_family, load_shakespeare, load_stl10, shakespeare_vocab,
)


def _write_idx(path, arr):
    arr = np.asarray(arr, np.uint8)
    with open(path, "wb") as f:
        # idx magic: 0x00000803 for 3-d uint8, 0x00000801 for 1-d
        f.write(struct.pack(">I", 0x00000800 | arr.ndim))
        f.write(struct.pack(">" + "I" * arr.ndim, *arr.shape))
        f.write(arr.tobytes())


class TestMnistReader:
    def test_roundtrip(self, tmp_path):
        base = tmp_path / "mnist"
        base.mkdir()
        imgs = np.random.randint(0, 255, (10, 28, 28), np.uint8)
        labels = np.random.randint(0, 10, (10,), np.uint8)
        timgs = imgs[:4]
        tlabels = labels[:4]
        _write_idx(base / "train-images-idx3-ubyte", imgs)
        _write_idx(base / "train-labels-idx1-ubyte", labels)
        _write_idx(base / "t10k-images-idx3-ubyte", timgs)
        _write_idx(base / "t10k-labels-idx1-ubyte", tlabels)
        splits = load_mnist_family("mnist", str(tmp_path))
        assert splits.train_x.shape == (10, 28, 28, 1)
        assert splits.train_x.dtype == np.float32
        np.testing.assert_array_equal(splits.train_y, labels)

    def test_gzipped(self, tmp_path):
        base = tmp_path / "mnist"
        base.mkdir()
        imgs = np.zeros((3, 28, 28), np.uint8)
        labels = np.asarray([1, 2, 3], np.uint8)
        for stem, arr in [("train-images-idx3-ubyte", imgs),
                          ("train-labels-idx1-ubyte", labels),
                          ("t10k-images-idx3-ubyte", imgs),
                          ("t10k-labels-idx1-ubyte", labels)]:
            raw_path = base / stem
            _write_idx(raw_path, arr)
            with open(raw_path, "rb") as f:
                data = f.read()
            with gzip.open(str(raw_path) + ".gz", "wb") as f:
                f.write(data)
            os.unlink(raw_path)
        splits = load_mnist_family("mnist", str(tmp_path))
        np.testing.assert_array_equal(splits.train_y, [1, 2, 3])


class TestCifarReader:
    def test_cifar10(self, tmp_path):
        base = tmp_path / "cifar-10-batches-py"
        base.mkdir()
        rng = np.random.RandomState(0)
        for i in range(1, 6):
            with open(base / f"data_batch_{i}", "wb") as f:
                pickle.dump({b"data": rng.randint(
                    0, 255, (4, 3072), np.uint8),
                    b"labels": rng.randint(0, 10, 4).tolist()}, f)
        with open(base / "test_batch", "wb") as f:
            pickle.dump({b"data": rng.randint(0, 255, (2, 3072), np.uint8),
                         b"labels": [1, 2]}, f)
        splits = load_cifar("cifar10", str(tmp_path))
        assert splits.train_x.shape == (20, 32, 32, 3)
        assert splits.test_x.shape == (2, 32, 32, 3)
        np.testing.assert_array_equal(splits.test_y, [1, 2])


class TestTFFReaders:
    def test_emnist(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        base = tmp_path / "emnist"
        base.mkdir()
        with h5py.File(base / "fed_emnist_digitsonly_train.h5", "w") as f:
            ex = f.create_group("examples")
            for cid, n in [("writer_a", 5), ("writer_b", 3)]:
                g = ex.create_group(cid)
                g.create_dataset("pixels", data=np.random.rand(
                    n, 28, 28).astype(np.float32))
                g.create_dataset("label", data=np.arange(n) % 10)
        # train-only fixture: the missing test split now raises
        # without the explicit opt-in (ISSUE 3)
        splits = load_emnist(str(tmp_path), full=False,
                             allow_train_as_test=True)
        assert splits.train_x.shape == (8, 28, 28, 1)
        assert len(splits.client_partitions) == 2
        assert [len(p) for p in splits.client_partitions] == [5, 3]
        # natural partition indices are disjoint & complete
        all_idx = np.sort(np.concatenate(splits.client_partitions))
        np.testing.assert_array_equal(all_idx, np.arange(8))

    def test_shakespeare(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        base = tmp_path / "shakespeare"
        base.mkdir()
        text = ("To be, or not to be: that is the question" * 5)
        with h5py.File(base / "shakespeare_train.h5", "w") as f:
            ex = f.create_group("examples")
            g = ex.create_group("HAMLET")
            g.create_dataset(
                "snippets",
                data=np.asarray([text.encode()], dtype=object),
                dtype=h5py.string_dtype())
        splits = load_shakespeare(str(tmp_path), seq_len=20)
        assert splits.train_x.shape[1] == 20
        # next-char targets are shifted by one
        np.testing.assert_array_equal(
            np.asarray(splits.train_x)[0, 1:],
            np.asarray(splits.train_y)[0, :-1])
        vocab = shakespeare_vocab()
        assert len(vocab) == 86  # exact TFF vocabulary


class TestLibSVMReader:
    def test_higgs(self, tmp_path):
        base = tmp_path / "higgs"
        base.mkdir()
        rows = []
        rng = np.random.RandomState(0)
        for i in range(1200):
            label = rng.choice([-1, 1])
            feats = " ".join(f"{j+1}:{rng.rand():.4f}" for j in range(5))
            rows.append(f"{label} {feats}")
        (base / "HIGGS").write_text("\n".join(rows))
        splits = load_libsvm("higgs", str(tmp_path))
        assert splits.train_x.shape[0] == 200  # last 1000 become test
        assert set(np.unique(splits.train_y)) <= {0, 1}


class TestAdultReader:
    def test_shared_encoding(self, tmp_path):
        base = tmp_path / "adult"
        base.mkdir()
        header = None
        train_rows = [
            "39, State-gov, 77516, Bachelors, 13, Never-married, "
            "Adm-clerical, Not-in-family, White, Male, 2174, 0, 40, "
            "United-States, <=50K",
            "50, Self-emp, 83311, HS-grad, 9, Married, Exec, Husband, "
            "Black, Female, 0, 0, 13, Holand-Netherlands, >50K",
        ] * 3
        test_rows = [
            "25, Private, 226802, 11th, 7, Never-married, "
            "Machine-op-inspct, Own-child, White, Male, 0, 0, 40, "
            "United-States, <=50K.",
        ] * 2
        (base / "adult.data").write_text("\n".join(train_rows))
        (base / "adult.test").write_text("header\n" + "\n".join(test_rows))
        splits = load_adult(str(tmp_path))
        assert splits.train_x.shape == (6, 14)
        assert splits.test_x.shape == (2, 14)
        assert splits.sensitive_values is not None
        assert set(np.unique(splits.train_y)) == {0, 1}


class TestSTL10Reader:
    def test_binary(self, tmp_path):
        base = tmp_path / "stl10_binary"
        base.mkdir()
        rng = np.random.RandomState(0)
        for split, n in [("train", 4), ("test", 2)]:
            rng.randint(0, 255, (n, 3, 96, 96), dtype=np.uint8) \
                .tofile(base / f"{split}_X.bin")
            (rng.randint(1, 11, n, dtype=np.uint8)) \
                .tofile(base / f"{split}_y.bin")
        splits = load_stl10(str(tmp_path))
        assert splits.train_x.shape == (4, 96, 96, 3)
        assert splits.train_y.min() >= 0 and splits.train_y.max() <= 9


def test_get_dataset_dispatch_natural_partitions(tmp_path):
    h5py = pytest.importorskip("h5py")
    base = tmp_path / "emnist"
    base.mkdir()
    with h5py.File(base / "fed_emnist_digitsonly_train.h5", "w") as f:
        ex = f.create_group("examples")
        for cid in ("a", "b", "c"):
            g = ex.create_group(cid)
            g.create_dataset("pixels",
                             data=np.random.rand(4, 28, 28)
                             .astype(np.float32))
            g.create_dataset("label", data=np.arange(4) % 10)
    cfg = DataConfig(dataset="emnist", data_dir=str(tmp_path),
                     allow_train_as_test=True)  # train-only fixture
    splits = get_dataset(cfg, num_clients=3)
    assert len(splits.client_partitions) == 3


class TestSvmlightRobustness:
    """The native parser is a pure accelerator (ADVICE r4): input it
    rejects must fall through to sklearn, and the incremental .bz2
    reader must match bz2.decompress on multi-stream files."""

    def test_read_file_bytes_multistream_bz2(self, tmp_path):
        import bz2
        from fedtorch_tpu.data.datasets import _read_file_bytes
        payload = b"1 1:0.5 2:1.0\n" * 2000
        p = tmp_path / "x.bz2"
        # two concatenated streams + an empty third (pbzip2 shape)
        p.write_bytes(bz2.compress(payload[:11000])
                      + bz2.compress(payload[11000:])
                      + bz2.compress(b""))
        assert bytes(_read_file_bytes(str(p))) == payload

    def test_read_file_bytes_plain(self, tmp_path):
        from fedtorch_tpu.data.datasets import _read_file_bytes
        payload = b"-1 3:2.5\n" * 100
        p = tmp_path / "y.txt"
        p.write_bytes(payload)
        assert bytes(_read_file_bytes(str(p))) == payload

    def test_native_rejection_falls_back_to_sklearn(self, tmp_path,
                                                    capsys):
        from fedtorch_tpu.data.datasets import _read_svmlight_dense
        from fedtorch_tpu.native.host_pipeline import native_available
        if not native_available():
            import pytest
            pytest.skip("native library unavailable")
        # sklearn and the native parser must agree on a well-formed
        # file; a native-rejected file must not crash the load
        p = tmp_path / "ok.txt"
        p.write_bytes(b"1 1:0.5 3:2.0\n-1 2:1.5\n")
        x, y = _read_svmlight_dense(str(p))
        assert x.shape == (2, 3)
        bad = tmp_path / "bad.bz2"
        bad.write_bytes(b"NOT A BZ2 FILE")
        try:
            _read_svmlight_dense(str(bad))
        except Exception as e:
            # sklearn also rejects it — but it must be SKLEARN's
            # error (the native path's OSError was absorbed)
            assert "bz2" not in type(e).__module__
        err = capsys.readouterr().err
        assert "falling back to sklearn" in err

    def test_parse_svmlight_accepts_bytearray(self):
        import numpy as np
        from fedtorch_tpu.native.host_pipeline import (
            native_available, parse_svmlight,
        )
        if not native_available():
            import pytest
            pytest.skip("native library unavailable")
        buf = bytearray(b"1 1:0.5 3:2.0\n-1 2:1.5\n")
        dense, labels = parse_svmlight(buf)
        d2, l2 = parse_svmlight(bytes(buf))
        assert (dense == d2).all() and (labels == l2).all()
        # no trailing newline: in-place append branch
        d3, _ = parse_svmlight(bytearray(b"1 1:0.5"))
        assert d3.shape == (1, 1) and np.isclose(d3[0, 0], 0.5)
