"""Tensor-parallel transformer forward (parallel/tensor.py).

GSPMD sharding must be numerically transparent: the TP (and DP x TP)
forward equals the single-device forward to float tolerance, for mesh
widths that do and do not divide the feature dimensions."""
import numpy as np
import jax
import pytest
from jax.sharding import Mesh

from fedtorch_tpu.models.transformer import TransformerLM
from fedtorch_tpu.parallel.tensor import tp_apply, transformer_tp_specs


def _model_and_toks(d_model=32, heads=4, seq=32, vocab=64):
    model = TransformerLM(vocab_size=vocab, d_model=d_model,
                          num_heads=heads, num_layers=2, max_len=seq)
    toks = jax.random.randint(jax.random.key(1), (4, seq), 0, vocab)
    params = model.init(jax.random.key(0), toks)["params"]
    return model, params, toks


@pytest.mark.parametrize("n_tp", [2, 4, 8])
def test_tp_matches_dense(n_tp):
    model, params, toks = _model_and_toks()
    mesh = Mesh(np.asarray(jax.devices()[:n_tp]), ("tp",))
    dense = model.apply({"params": params}, toks)
    out = tp_apply(model, params, toks, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_tp_indivisible_features_fall_back_replicated():
    """A mesh width that does not divide the sharded feature dims must
    degrade those leaves to replicated (not crash), staying exact."""
    from jax.sharding import PartitionSpec as P

    model, params, toks = _model_and_toks(d_model=25, heads=5)
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("tp",))
    # 25 % 4 != 0 -> row-parallel proj kernel falls back; qkv column dim
    # is 75 which also fails -> replicated
    specs = transformer_tp_specs(params, mesh=mesh)
    assert specs["block_0"]["attn"]["proj"]["kernel"] == P()
    assert specs["block_0"]["attn"]["qkv"]["kernel"] == P()
    # mlp hidden is 4*25=100, divisible by 4 -> still sharded
    assert specs["block_0"]["mlp_in"]["kernel"] == P(None, "tp")
    dense = model.apply({"params": params}, toks)
    out = tp_apply(model, params, toks, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_dp_tp_2d_mesh():
    model, params, toks = _model_and_toks()
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                ("dp", "tp"))
    dense = model.apply({"params": params}, toks)
    out = tp_apply(model, params, toks, mesh, dp_axis="dp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_specs_shape():
    """Column/row rules land on the right leaves; all else replicated."""
    from jax.sharding import PartitionSpec as P

    _, params, _ = _model_and_toks()
    specs = transformer_tp_specs(params)
    b0 = specs["block_0"]
    assert b0["attn"]["qkv"]["kernel"] == P(None, "tp")
    assert b0["attn"]["proj"]["kernel"] == P("tp", None)
    assert b0["mlp_in"]["kernel"] == P(None, "tp")
    assert b0["mlp_in"]["bias"] == P("tp")
    assert b0["mlp_out"]["kernel"] == P("tp", None)
    assert specs["head"]["kernel"] == P()
    assert specs["tok_embed"]["embedding"] == P()
    assert specs["block_0"]["ln1"]["scale"] == P()
