"""Privacy plane tests (docs/robustness.md "Privacy plane"): the
stdlib RDP accountant, the in-jit DP-FedAvg aggregation stage at the
``_round_core`` seam, the shared radial-clip machinery it borrows from
``norm_bound``, the config refusals, and the epsilon-budget lifecycle.

The bars, per the engine-wide contracts:

* the accountant matches the closed-form pure-Gaussian epsilon within
  1% on the default order grid, amplifies under subsampling, persists
  atomically, resume-adopts like program_costs.json, and refuses (by
  name) a document from a different mechanism;
* the armed round program traces exactly once, replays bitwise from
  the seed, and noises at exactly sigma = z * clip / k;
* DP off is FREE: zero extra pytree leaves, the lowered HLO is
  byte-identical to a build that never heard of DP;
* budget degrade swaps the traced noise-scale leaf's DATA — no
  retrace.
"""
import json
import math
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    CheckpointConfig, DataConfig, ExperimentConfig, FaultConfig,
    FederatedConfig, ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.robustness.aggregators import (
    _unit_updates, radial_clip, radial_distances,
)
from fedtorch_tpu.robustness.privacy import (
    ACCOUNTANT_FILE, ACCOUNTANT_SCHEMA, PrivacyAccountant,
    calibrate_noise_multiplier, closed_form_epsilon, gaussian_rdp,
    rdp_to_epsilon, subsampled_gaussian_rdp,
)
from fedtorch_tpu.utils.tracing import RecompilationSentinel

DELTA = 1e-5


def make_cfg(fault, *, num_clients=8, sync_mode="sync", plane="device",
             num_comms=6, run_dir=None, rate=0.5, algorithm="fedavg"):
    ckpt = CheckpointConfig(run_dir=run_dir, debug=False) \
        if run_dir else CheckpointConfig()
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20,
                        batch_size=16, synthetic_alpha=0.5,
                        synthetic_beta=0.5, data_plane=plane),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients,
            num_comms=num_comms, online_client_rate=rate,
            algorithm=algorithm, sync_type="local_step",
            sync_mode=sync_mode),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.3, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        checkpoint=ckpt,
        fault=fault,
    ).finalize()


def make_trainer(fault, **kw):
    cfg = make_cfg(fault, **kw)
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    if cfg.federated.sync_mode == "async":
        from fedtorch_tpu.async_plane import AsyncFederatedTrainer
        return AsyncFederatedTrainer(cfg, model, make_algorithm(cfg),
                                     data.train)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)


def fingerprint(tree):
    return [np.asarray(x).tobytes() for x in jax.tree.leaves(tree)]


DP = dict(dp_noise_multiplier=1.0, dp_clip_norm=0.5, dp_delta=DELTA)


# -- the accountant (host-side, stdlib, never imports jax) ------------------
class TestAccountant:
    def test_matches_closed_form_pure_gaussian(self):
        """No subsampling (q=1): the RDP grid's epsilon must sit within
        1% of the analytic strong-composition bound
        T/(2 z^2) + sqrt(2 T ln(1/delta)) / z."""
        z, T = 1.1, 100
        acc = PrivacyAccountant(z, DELTA)
        acc.charge(1.0, rounds=T)
        cf = closed_form_epsilon(z, T, DELTA)
        assert abs(acc.epsilon() - cf) / cf < 0.01

    def test_rdp_grid_tracks_closed_form_across_regimes(self):
        """The grid stays within 1% of the analytic bound well outside
        the single parity point above (both are valid bounds; neither
        dominates everywhere, so parity — not ordering — is the pin)."""
        for z, T in ((0.7, 10), (1.0, 50), (2.0, 500)):
            acc = PrivacyAccountant(z, DELTA)
            acc.charge(1.0, rounds=T)
            cf = closed_form_epsilon(z, T, DELTA)
            assert abs(acc.epsilon() - cf) / cf < 0.01

    def test_subsampling_amplifies_and_is_monotone_in_q(self):
        eps = []
        for q in (0.05, 0.25, 0.5, 1.0):
            acc = PrivacyAccountant(1.0, DELTA)
            acc.charge(q, rounds=50)
            eps.append(acc.epsilon())
        assert eps == sorted(eps)
        assert eps[0] < eps[-1] * 0.5  # amplification actually bites

    def test_subsampled_rdp_limits(self):
        """q=0 charges nothing; q=1 is exactly the Gaussian bound."""
        assert subsampled_gaussian_rdp(0.0, 1.0, 8.0) == 0.0
        assert subsampled_gaussian_rdp(1.0, 1.0, 8.0) == \
            gaussian_rdp(1.0, 8.0)
        assert subsampled_gaussian_rdp(0.3, 1.0, 8.0) < \
            gaussian_rdp(1.0, 8.0)

    def test_fractional_orders_cgf_interpolation(self):
        """Fractional alpha is charged by CGF-convexity interpolation,
        not rounded up: still a valid upper bound (>= the exact value
        is untestable directly, so the pins are monotonicity in alpha
        plus never-worse-than-ceil), strictly tighter than the old
        ceil(alpha) charge for floor(alpha) >= 2, exact at integer
        alpha, and the (1, 2) anchor reproduces the RDP(2) charge."""
        from fedtorch_tpu.robustness.privacy import (
            DEFAULT_ORDERS, _integer_subsampled_rdp,
        )
        q, z = 0.02, 1.1
        # monotone over the whole default grid (RDP is nondecreasing
        # in alpha; the chord interpolation must preserve that)
        grid = [subsampled_gaussian_rdp(q, z, a)
                for a in sorted(DEFAULT_ORDERS)]
        assert all(b >= a - 1e-15 for a, b in zip(grid, grid[1:]))
        for alpha in (2.5, 3.25, 5.75, 10.5, 40.125):
            new = subsampled_gaussian_rdp(q, z, alpha)
            ceil_charge = _integer_subsampled_rdp(
                q, z, int(math.ceil(alpha)))
            assert new < ceil_charge  # strictly tighter, n >= 2
        for alpha in (2, 3, 7, 32):  # integers: the closed form itself
            assert subsampled_gaussian_rdp(q, z, float(alpha)) == \
                _integer_subsampled_rdp(q, z, alpha)
        # cgf(1) = 0 anchor: every order in (1, 2) charges RDP(2)
        r2 = _integer_subsampled_rdp(q, z, 2)
        for alpha in (1.125, 1.5, 1.875):
            assert abs(subsampled_gaussian_rdp(q, z, alpha) - r2) \
                < 1e-12 * max(r2, 1.0)

    def test_fractional_tightening_keeps_closed_form_bar(self):
        """The tightened fractional charge must not push the
        subsampled accountant ABOVE the old ceil-based epsilon (it can
        only lower the grid minimum), and the q=1 control stays on the
        existing 1% closed-form bar."""
        from fedtorch_tpu.robustness.privacy import (
            DEFAULT_ORDERS, _integer_subsampled_rdp, rdp_to_epsilon,
        )
        q, z, T = 0.1, 1.0, 200
        acc = PrivacyAccountant(z, DELTA)
        acc.charge(q, rounds=T)
        old_rdp = [T * (_integer_subsampled_rdp(q, z,
                                                max(int(math.ceil(a)),
                                                    2))
                        if 0.0 < q < 1.0 else gaussian_rdp(z, a))
                   for a in acc.orders]
        old_eps = rdp_to_epsilon(acc.orders, old_rdp, DELTA)
        assert acc.epsilon() <= old_eps * (1.0 + 1e-12)
        # q=1 control: the grid optimum still within 1% of closed form
        acc1 = PrivacyAccountant(z, DELTA)
        acc1.charge(1.0, rounds=T)
        cf = closed_form_epsilon(z, T, DELTA)
        assert abs(acc1.epsilon() - cf) / cf < 0.01

    def test_epsilon_zero_before_any_charge(self):
        assert PrivacyAccountant(1.0, DELTA).epsilon() == 0.0

    def test_charge_round_dedups_and_refuses_replay(self):
        """A resumed run re-entering an already-charged round index
        must not double-charge (the program_costs.json convention:
        adopt, never re-spend)."""
        acc = PrivacyAccountant(1.0, DELTA)
        assert acc.charge_round(0, 0.5)
        e1 = acc.epsilon()
        assert not acc.charge_round(0, 0.5)   # replayed round: no-op
        assert acc.epsilon() == e1
        assert acc.charge_round(1, 0.5)
        assert acc.epsilon() > e1

    def test_preview_epsilon_is_lookahead_not_spend(self):
        acc = PrivacyAccountant(1.0, DELTA)
        acc.charge_round(0, 0.5)
        spent = acc.epsilon()
        preview = acc.preview_epsilon(0.5)
        assert preview > spent
        assert acc.epsilon() == spent  # preview charged nothing
        acc.charge_round(1, 0.5)
        assert abs(acc.epsilon() - preview) < 1e-12

    def test_save_load_round_trip(self, tmp_path):
        acc = PrivacyAccountant(1.0, DELTA)
        for r in range(5):
            acc.charge_round(r, 0.5)
        assert acc.save(str(tmp_path))
        fresh = PrivacyAccountant(1.0, DELTA)
        assert fresh.load_existing(str(tmp_path))
        assert fresh.epsilon() == acc.epsilon()
        assert fresh.charged_rounds == 5
        # adoption carries the replay guard across the restart
        assert not fresh.charge_round(4, 0.5)
        assert fresh.charge_round(5, 0.5)

    def test_load_missing_is_false_not_error(self, tmp_path):
        assert not PrivacyAccountant(1.0, DELTA).load_existing(
            str(tmp_path))

    def test_adopt_refuses_mechanism_mismatch_by_name(self, tmp_path):
        acc = PrivacyAccountant(1.0, DELTA)
        acc.charge_round(0, 0.5)
        acc.save(str(tmp_path))
        with pytest.raises(ValueError, match="noise_multiplier"):
            PrivacyAccountant(2.0, DELTA).load_existing(str(tmp_path))
        with pytest.raises(ValueError, match="delta"):
            PrivacyAccountant(1.0, 1e-6).load_existing(str(tmp_path))

    def test_adopt_refuses_foreign_schema_and_torn_doc(self):
        acc = PrivacyAccountant(1.0, DELTA)
        with pytest.raises(ValueError, match="schema"):
            acc.adopt_state({"schema": "somebody.else/v9"})
        doc = PrivacyAccountant(1.0, DELTA).state()
        doc["rdp"] = doc["rdp"][:3]
        with pytest.raises(ValueError, match="torn"):
            acc.adopt_state(doc)

    def test_corrupt_file_raises_not_resets(self, tmp_path):
        """A foreign/corrupt accountant file must refuse, not silently
        forget spend."""
        (tmp_path / ACCOUNTANT_FILE).write_text("{not json")
        with pytest.raises(ValueError, match="unreadable"):
            PrivacyAccountant(1.0, DELTA).load_existing(str(tmp_path))

    def test_state_doc_shape(self):
        acc = PrivacyAccountant(1.0, DELTA)
        acc.charge_round(0, 0.5)
        doc = acc.state()
        assert doc["schema"] == ACCOUNTANT_SCHEMA
        assert doc["charged_rounds"] == 1
        assert doc["epsilon_spent"] == acc.epsilon()
        # round-trips through json (the persistence format)
        assert json.loads(json.dumps(doc)) is not None

    def test_calibration_hits_target(self):
        z = calibrate_noise_multiplier(8.0, 50, 0.5, DELTA)
        acc = PrivacyAccountant(z, DELTA)
        acc.charge(0.5, rounds=50)
        assert acc.epsilon() <= 8.0
        assert acc.epsilon() > 8.0 * 0.98  # not wastefully loose

    def test_invalid_parameters_raise(self):
        with pytest.raises(ValueError):
            PrivacyAccountant(0.0, DELTA)
        with pytest.raises(ValueError):
            PrivacyAccountant(1.0, 0.0)
        with pytest.raises(ValueError):
            PrivacyAccountant(1.0, DELTA).charge(1.5)
        with pytest.raises(ValueError):
            calibrate_noise_multiplier(0.0, 10, 0.5, DELTA)

    def test_rdp_to_epsilon_picks_the_best_order(self):
        orders = (2.0, 8.0, 32.0)
        rdp = [gaussian_rdp(1.0, a) * 10 for a in orders]
        eps = rdp_to_epsilon(orders, rdp, DELTA)
        assert eps == min(
            r + math.log(1.0 / DELTA) / (a - 1.0)
            for a, r in zip(orders, rdp))


# -- config refusals --------------------------------------------------------
class TestConfigRefusals:
    def test_dp_with_norm_bound_refused_by_name(self):
        with pytest.raises(ValueError, match="norm_bound"):
            make_cfg(FaultConfig(robust_agg="norm_bound", **DP))

    def test_dp_with_structured_payload_refused_by_name(self):
        with pytest.raises(ValueError, match="scaffold"):
            make_cfg(FaultConfig(**DP), algorithm="scaffold")

    def test_budget_without_dp_refused(self):
        with pytest.raises(ValueError, match="dp_epsilon_budget"):
            make_cfg(FaultConfig(dp_epsilon_budget=4.0))

    def test_bad_knobs_refused(self):
        with pytest.raises(ValueError, match="dp_noise_multiplier"):
            make_cfg(FaultConfig(dp_noise_multiplier=-1.0))
        with pytest.raises(ValueError, match="dp_clip_norm"):
            make_cfg(FaultConfig(dp_noise_multiplier=1.0,
                                 dp_clip_norm=0.0))
        with pytest.raises(ValueError, match="dp_delta"):
            make_cfg(FaultConfig(dp_noise_multiplier=1.0, dp_delta=2.0))
        with pytest.raises(ValueError, match="dp_budget_action"):
            make_cfg(FaultConfig(dp_budget_action="panic", **DP))

    def test_dp_composes_with_non_clipping_robust_rules(self):
        for agg in ("trimmed_mean", "median", "krum"):
            make_cfg(FaultConfig(robust_agg=agg, **DP))


# -- the shared radial-clip machinery (satellite: norm_bound factoring) -----
class TestRadialClipFactoring:
    """``radial_distances``/``radial_clip`` were factored OUT of
    ``norm_bound`` so the DP stage shares one clip implementation.
    Pin them bitwise against an inline reimplementation of the
    original formulas — a numerics drift here silently moves every
    pinned norm_bound trajectory."""

    def _crafted(self, k=6, dim=7, seed=3):
        rng = np.random.RandomState(seed)
        w = rng.uniform(0.5, 2.0, size=k).astype(np.float32)
        w[1] = 0.0  # a zero-weight client rides along
        deltas = rng.randn(k, dim).astype(np.float32)
        payloads = {"w": jnp.asarray(deltas * w[:, None])}
        m = {"w": jnp.asarray(rng.randn(dim).astype(np.float32))}
        return payloads, jnp.asarray(w), m

    def test_distances_match_inline_formula(self):
        payloads, w, m = self._crafted()
        unit = _unit_updates(payloads, w)
        got = np.asarray(radial_distances(unit, m))
        # original inline spelling (f32 leaf-wise sq accumulation,
        # then sqrt), recomputed here independently of the helper —
        # same ops so the comparison is bitwise
        u = unit["w"].astype(jnp.float32)
        diff = u - m["w"][None].astype(jnp.float32)
        want = np.asarray(jnp.sqrt(
            jnp.zeros(()) + jnp.sum(jnp.square(diff), axis=(1,))))
        np.testing.assert_array_equal(got, want)

    def test_origin_distances_are_update_norms(self):
        payloads, w, _ = self._crafted()
        unit = _unit_updates(payloads, w)
        got = np.asarray(radial_distances(unit))
        uf = unit["w"].astype(jnp.float32)
        want = np.asarray(jnp.sqrt(
            jnp.zeros(()) + jnp.sum(jnp.square(uf), axis=(1,))))
        np.testing.assert_array_equal(got, want)
        assert got[1] == 0.0  # zero-weight client measures zero

    def test_centered_clip_matches_inline_formula(self):
        payloads, w, m = self._crafted()
        scale = jnp.asarray(
            np.linspace(0.2, 1.0, w.shape[0]).astype(np.float32))
        got = np.asarray(radial_clip(payloads, w, scale, center=m)["w"])
        s = np.asarray(scale)[:, None]
        wm = (np.asarray(w) * (1.0 - np.asarray(scale)))[:, None]
        want = np.asarray(payloads["w"]) * s \
            + wm * np.asarray(m["w"])[None]
        np.testing.assert_array_equal(got, want)

    def test_origin_clip_is_pure_shrink(self):
        payloads, w, _ = self._crafted()
        scale = jnp.full((w.shape[0],), 0.5, jnp.float32)
        got = np.asarray(radial_clip(payloads, w, scale)["w"])
        np.testing.assert_array_equal(
            got, np.asarray(payloads["w"]) * 0.5)


# -- the in-jit DP stage ----------------------------------------------------
class TestDPRound:
    def test_sync_round_replays_bitwise_and_traces_once(self):
        def run():
            t = make_trainer(FaultConfig(**DP))
            server, clients = t.init_state(jax.random.key(0))
            fps = []
            with RecompilationSentinel() as s:
                for _ in range(3):
                    server, clients, m = t.run_round(server, clients)
                    fps.append(fingerprint(server.params))
            sc = t.round_host_scalars(clients, m)
            return fps, sum(s.counts.values()), sc

        fps1, traces, sc = run()
        fps2, _, _ = run()
        assert fps1 == fps2
        assert traces == 1
        # sigma = z * clip / k_online = 1.0 * 0.5 / 4
        assert sc["dp_noise_sigma"] == pytest.approx(0.125)
        assert 0.0 <= sc["dp_clipped_frac"] <= 1.0

    def test_noise_actually_perturbs_the_estimate(self):
        t_on = make_trainer(FaultConfig(**DP))
        t_off = make_trainer(FaultConfig())
        s_on, c_on = t_on.init_state(jax.random.key(0))
        s_off, c_off = t_off.init_state(jax.random.key(0))
        s_on, _, _ = t_on.run_round(s_on, c_on)
        s_off, _, _ = t_off.run_round(s_off, c_off)
        assert fingerprint(s_on.params) != fingerprint(s_off.params)

    def test_off_is_hlo_byte_identical_and_leaf_free(self):
        """Disarmed DP knobs (clip/delta/action all non-default) must
        lower to the byte-identical program with no aux wrap — DP off
        costs literally nothing."""
        t_plain = make_trainer(FaultConfig())
        t_disarmed = make_trainer(FaultConfig(
            dp_noise_multiplier=0.0, dp_clip_norm=9.0, dp_delta=0.5,
            dp_budget_action="degrade"))
        s1, c1 = t_plain.init_state(jax.random.key(0))
        s2, c2 = t_disarmed.init_state(jax.random.key(0))
        assert not (isinstance(s2.aux, dict)
                    and "dp_noise_scale" in s2.aux)
        hlo1 = t_plain._round_jit.lower(
            s1, c1, t_plain.data, t_plain.val_data).as_text()
        hlo2 = t_disarmed._round_jit.lower(
            s2, c2, t_disarmed.data, t_disarmed.val_data).as_text()
        assert hlo1 == hlo2
        _, _, m = t_plain.run_round(s1, c1)
        assert m.dp_clipped_frac is None and m.dp_noise_sigma is None

    def test_degrade_swaps_noise_scale_without_retrace(self):
        t = make_trainer(FaultConfig(**DP))
        server, clients = t.init_state(jax.random.key(0))
        with RecompilationSentinel() as s:
            server, clients, m = t.run_round(server, clients)
            server = t.dp_set_noise_scale(server, 0.0)
            server, clients, m = t.run_round(server, clients)
            traces = sum(s.counts.values())
        assert traces == 1  # data swap, not a retrace
        sc = t.round_host_scalars(clients, m)
        assert sc["dp_noise_sigma"] == 0.0
        assert sc["dp_clipped_frac"] > 0.0  # clip still applies

    def test_degraded_round_is_noise_free(self):
        """sigma=0 through the traced program equals the clip-only
        trajectory bitwise — degrade is exactly 'stop noising'."""
        def run(scale):
            t = make_trainer(FaultConfig(**DP))
            server, clients = t.init_state(jax.random.key(0))
            server = t.dp_set_noise_scale(server, scale)
            server, clients, _ = t.run_round(server, clients)
            return fingerprint(server.params)

        assert run(0.0) == run(0.0)
        assert run(0.0) != run(1.0)

    def test_set_noise_scale_refuses_when_off(self):
        t = make_trainer(FaultConfig())
        server, _ = t.init_state(jax.random.key(0))
        with pytest.raises(ValueError):
            t.dp_set_noise_scale(server, 0.0)

    def test_async_commit_charges_buffer_width(self):
        """The commit program noises at sigma = z * clip / m with m
        the REAL commit buffer size, not the sync cohort width."""
        t = make_trainer(FaultConfig(**DP), sync_mode="async")
        server, clients = t.init_state(jax.random.key(0))
        with RecompilationSentinel() as s:
            for _ in range(3):
                server, clients, m = t.run_round(server, clients)
            traces = sum(s.counts.values())
        t.invalidate_stream()
        assert traces == 1
        sc = t.round_host_scalars(clients, m)
        assert sc["dp_noise_sigma"] == pytest.approx(
            1.0 * 0.5 / t.buffer_size)

    def test_async_degrade_reaches_through_ring_wrap(self):
        t = make_trainer(FaultConfig(**DP), sync_mode="async")
        server, clients = t.init_state(jax.random.key(0))
        server, clients, _ = t.run_round(server, clients)
        server = t.dp_set_noise_scale(server, 0.0)
        server, clients, m = t.run_round(server, clients)
        t.invalidate_stream()
        sc = t.round_host_scalars(clients, m)
        assert sc["dp_noise_sigma"] == 0.0

    def test_dp_composes_with_trimmed_mean(self):
        t = make_trainer(FaultConfig(robust_agg="trimmed_mean",
                                     robust_trim_frac=0.25, **DP))
        server, clients = t.init_state(jax.random.key(0))
        for _ in range(2):
            server, clients, m = t.run_round(server, clients)
        sc = t.round_host_scalars(clients, m)
        assert sc["dp_noise_sigma"] > 0.0
        assert all(np.isfinite(np.asarray(x)).all()
                   for x in jax.tree.leaves(server.params))


# -- budget lifecycle through the real CLI loop (slow lane) -----------------
@pytest.mark.slow
class TestBudgetLifecycle:
    def _drill(self, action, tmp_path):
        from fedtorch_tpu.cli import run_experiment
        from fedtorch_tpu.telemetry import read_health
        from fedtorch_tpu.telemetry.schema import iter_jsonl

        q, rounds, half = 0.5, 6, 3
        affordable = PrivacyAccountant(1.0, DELTA)
        affordable.charge(q, rounds=half)
        budget = affordable.epsilon() * 1.0001
        run_dir = str(tmp_path / action)
        cfg = make_cfg(FaultConfig(dp_epsilon_budget=budget,
                                   dp_budget_action=action, **DP),
                       run_dir=run_dir, num_comms=rounds)
        res = run_experiment(cfg)
        events = [e for e in iter_jsonl(
            os.path.join(run_dir, "events.jsonl"))
            if e.get("event") == "privacy.budget_exhausted"]
        rows = [r for r in iter_jsonl(
            os.path.join(run_dir, "metrics.jsonl")) if "round" in r]
        with open(os.path.join(run_dir, ACCOUNTANT_FILE)) as f:
            acc_doc = json.load(f)
        return (res, events, rows, read_health(run_dir)["intent"],
                acc_doc, budget, rounds, half)

    def test_stop_ends_at_last_affordable_round(self, tmp_path):
        res, events, rows, intent, acc_doc, budget, _, half = \
            self._drill("stop", tmp_path)
        assert len(events) == 1 and events[0]["action"] == "stop"
        assert len(rows) == half == res["dp_exhausted_at_round"]
        assert intent == "complete"  # a stopped run is a FINISHED run
        assert acc_doc["epsilon_spent"] <= budget * 1.0001
        assert res["dp"]["exhausted"]
        assert rows[-1]["dp_epsilon_spent"] == pytest.approx(
            acc_doc["epsilon_spent"])

    def test_degrade_finishes_noise_free(self, tmp_path):
        res, events, rows, intent, acc_doc, budget, rounds, half = \
            self._drill("degrade", tmp_path)
        assert len(events) == 1 and events[0]["action"] == "degrade"
        assert len(rows) == rounds  # never wedges
        assert intent == "degraded"
        assert rows[half - 1]["dp_noise_sigma"] > 0.0
        assert rows[-1]["dp_noise_sigma"] == 0.0
        assert acc_doc["epsilon_spent"] <= budget * 1.0001  # frozen
        assert res["dp"]["degraded"]

    def test_resume_adopts_spend(self, tmp_path):
        """A checkpointed DP run resumed into a fresh process adopts
        the persisted accountant — spend survives, no double-charge."""
        from fedtorch_tpu.cli import run_experiment
        run_dir = str(tmp_path / "resume")
        cfg = make_cfg(FaultConfig(**DP), run_dir=run_dir, num_comms=4)
        run_experiment(cfg)
        with open(os.path.join(run_dir, ACCOUNTANT_FILE)) as f:
            first = json.load(f)
        assert first["charged_rounds"] == 4
        # same dir, same mechanism: the next run adopts rather than
        # restarting the ledger at zero
        acc = PrivacyAccountant(1.0, DELTA)
        assert acc.load_existing(run_dir)
        assert acc.epsilon() == first["epsilon_spent"]
        assert not acc.charge_round(3, 0.5)
