"""Watchdog drill (ISSUE 4 acceptance): a deliberately wedged round —
one blocked worker in a 2-process pod — converts to exit code 75
within the timeout, with thread stacks in the log.

Process 1 stops participating before round 2; process 0 blocks inside
the round's DCN collective (the silent lost-host hang of
docs/multihost.md "Failure model"). Both processes' StallWatchdogs
must fire: exit code 75 (restartable — the harness relaunches on the
surviving slice) and a full thread-stack dump naming the wedged
MainThread.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from mh_common import run_workers  # noqa: E402

_WORKER = os.path.join(os.path.dirname(__file__), "watchdog_worker.py")
TIMEOUT_S = 6.0


@pytest.mark.slow
def test_wedged_round_exits_75_with_stacks():
    outs = run_workers(_WORKER, [TIMEOUT_S], 2, timeout=180,
                       expect_rc=75)
    for pid, out in enumerate(outs):
        # both completed round 0 and 1, neither completed round 2
        assert f"ROUND pid={pid} r=1" in out, out
        assert f"ROUND pid={pid} r=2" not in out, out
        # the watchdog named the failure and dumped every thread
        assert "StallWatchdog: no round completed in" in out, out
        assert "--- Thread MainThread" in out, out
        # the wedged collective (pid 0) / sleep (pid 1) is visible in
        # the dump — the post-mortem an operator needs
        assert "stall-watchdog" in out, out
    assert "WEDGE pid=1" in outs[1], outs[1]
