"""Transformer LM: causal correctness, federated training, and the
sequence-parallel long-context path."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig, OptimConfig,
    TrainConfig,
)
from fedtorch_tpu.models import define_model
from fedtorch_tpu.models.transformer import TransformerLM, \
    long_context_apply


def _model(seq_len=32):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="shakespeare"),
        model=ModelConfig(arch="transformer", rnn_seq_len=seq_len,
                          rnn_hidden_size=32, mlp_num_layers=2,
                          vocab_size=86))
    return define_model(cfg, batch_size=4)


def test_shapes_and_causality():
    model = _model()
    params = model.init(jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (4, 32), 0, 86)
    logits = model.apply(params, toks)
    assert logits.shape == (4, 32, 86)
    # causality: changing a future token must not affect earlier logits
    toks2 = toks.at[:, 20].set((toks[:, 20] + 1) % 86)
    logits2 = model.apply(params, toks2)
    np.testing.assert_allclose(np.asarray(logits[:, :20]),
                               np.asarray(logits2[:, :20]), atol=1e-5)
    assert not np.allclose(np.asarray(logits[:, 20:]),
                           np.asarray(logits2[:, 20:]))


def test_federated_training_converges():
    """Char-LM on a repetitive corpus: loss must drop fast."""
    cfg = ExperimentConfig(
        data=DataConfig(dataset="shakespeare", batch_size=8),
        federated=FederatedConfig(federated=True, num_clients=4,
                                  num_comms=10, online_client_rate=1.0,
                                  algorithm="fedavg",
                                  sync_type="local_step"),
        model=ModelConfig(arch="transformer", rnn_seq_len=16,
                          rnn_hidden_size=16, mlp_num_layers=1,
                          vocab_size=86),
        optim=OptimConfig(lr=0.05, weight_decay=0.0),
        train=TrainConfig(local_step=5),
    ).finalize()
    model = define_model(cfg, batch_size=8)
    # synthetic periodic char stream (period 4 -> highly learnable)
    rng = np.random.RandomState(0)
    stream = np.tile(np.asarray([5, 17, 42, 63]), 600)
    n_win = (len(stream) - 1) // 16
    x = stream[:n_win * 16].reshape(n_win, 16)
    y = stream[1:n_win * 16 + 1].reshape(n_win, 16)
    from fedtorch_tpu.data.batching import stack_partitions
    parts = np.array_split(rng.permutation(n_win), 4)
    data = stack_partitions(x, y, parts)
    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.parallel import FederatedTrainer
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
    server, clients = trainer.init_state(jax.random.key(0))
    first = None
    for _ in range(10):
        server, clients, m = trainer.run_round(server, clients)
        loss = float(jnp.sum(m.train_loss) / 4)
        if first is None:
            first = loss
    assert loss < first * 0.5, (first, loss)


@pytest.mark.parametrize("algorithm,fed_kw", [
    ("scaffold", {}),
    ("fedgate", {"compressed": True, "compressed_ratio": 0.5}),
    ("qsparse", {"compressed": True, "compressed_ratio": 0.5}),
    ("apfl", {"personal": True}),
    # the two hardest hooks: DRFA's two-phase round (kth-model snapshot
    # + second sampling + dual update) and qFFL's full-data loss pass
    ("fedavg", {"drfa": True, "drfa_gamma": 0.1,
                "online_client_rate": 0.5}),
    ("qffl", {"qffl_q": 1.0}),
])
def test_algorithm_zoo_composes_with_transformer(algorithm, fed_kw):
    """The aggregation families are pytree-generic: control variates,
    top-k wire formats, and personal models must run unchanged on the
    transformer (incl. a sparse-MoE variant), not just the MLP the
    dryrun matrix uses. One round each, finite loss."""
    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.parallel import FederatedTrainer

    rng = np.random.RandomState(1)
    x = rng.randint(0, 86, (32, 16)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    parts = [np.arange(i * 8, (i + 1) * 8) for i in range(4)]
    data = stack_partitions(x, y, parts)
    cfg = ExperimentConfig(
        data=DataConfig(dataset="shakespeare", batch_size=4),
        federated=FederatedConfig(**{
            "federated": True, "num_clients": 4,
            "online_client_rate": 1.0, "algorithm": algorithm,
            "sync_type": "local_step", **fed_kw}),
        model=ModelConfig(arch="transformer", rnn_seq_len=16,
                          rnn_hidden_size=8, mlp_num_layers=1,
                          moe_experts=2, moe_capacity_factor=1.5),
        optim=OptimConfig(lr=0.05, weight_decay=0.0),
        train=TrainConfig(local_step=2),
    ).finalize()
    model = define_model(cfg, batch_size=4)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
    server, clients = trainer.init_state(jax.random.key(0))
    _, _, m = trainer.run_round(server, clients)
    loss = float(m.train_loss.sum() / m.online_mask.sum())
    assert np.isfinite(loss)


def test_long_context_ring_matches_dense():
    """The ring-attention forward must equal the dense forward."""
    model = _model(seq_len=64)
    params = model.init(jax.random.key(0))
    toks = jax.random.randint(jax.random.key(2), (2, 64), 0, 86)
    dense = model.apply(params, toks)
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("sp",))
    ring = long_context_apply(model.module, params, toks, mesh)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               atol=3e-4, rtol=3e-4)


def test_large_e_dense_dispatch_warns():
    """E>=8 with dense dispatch is oracle mode at Ex the FLOPs; the
    factory nudges toward the measured sparse recommendation
    (MOE_AB_CPU.json: 8.6x executed-FLOPs ratio at E=16)."""
    import warnings

    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, ModelConfig,
    )
    from fedtorch_tpu.models import define_model
    cfg = ExperimentConfig(
        data=DataConfig(dataset="shakespeare", batch_size=2),
        model=ModelConfig(arch="transformer", moe_experts=8)).finalize()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        define_model(cfg, batch_size=2)
    assert any("moe_capacity_factor" in str(x.message) for x in w)
    # sparse dispatch silences it
    cfg2 = ExperimentConfig(
        data=DataConfig(dataset="shakespeare", batch_size=2),
        model=ModelConfig(arch="transformer", moe_experts=8,
                          moe_capacity_factor=1.25)).finalize()
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        define_model(cfg2, batch_size=2)
    assert not any("moe_capacity_factor" in str(x.message) for x in w2)
