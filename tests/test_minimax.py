"""AFL and DRFA minimax algorithms."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.algorithms.drfa import DRFA
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig, OptimConfig,
    TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer, evaluate


def _trainer(algorithm, lr=0.3, local_step=5, num_clients=8, rate=0.5,
             drfa=False, **fed_kw):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20,
                        batch_size=16, synthetic_alpha=1.0,
                        synthetic_beta=1.0),
        federated=FederatedConfig(federated=True, num_clients=num_clients,
                                  online_client_rate=rate,
                                  algorithm=algorithm, drfa=drfa,
                                  sync_type="local_step", **fed_kw),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=lr, weight_decay=0.0),
        train=TrainConfig(local_step=local_step),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=16)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)
    return trainer, data


def _run(trainer, rounds, seed=0):
    server, clients = trainer.init_state(jax.random.key(seed))
    for _ in range(rounds):
        server, clients, metrics = trainer.run_round(server, clients)
    return server, clients, metrics


class TestAFL:
    def test_config_coercion(self):
        trainer, _ = _trainer("afl")
        # afl forces local_step=1 + sync local_step (parameters.py:249-251)
        assert trainer.cfg.train.local_step == 1
        assert trainer.cfg.federated.sync_type == "local_step"
        assert trainer.local_steps == 1

    def test_lambda_on_simplex_after_rounds(self):
        trainer, _ = _trainer("afl", drfa_gamma=0.5)
        server, _, _ = _run(trainer, 5)
        lam = np.asarray(server.aux["lambda"])
        assert lam.sum() == pytest.approx(1.0, abs=1e-5)
        assert lam.min() > 0

    def test_lambda_concentrates_on_lossy_client(self):
        """The dual ascends toward high-loss clients."""
        trainer, _ = _trainer("afl", drfa_gamma=1.0, rate=1.0)
        server, clients, _ = _run(trainer, 8)
        lam = np.asarray(server.aux["lambda"])
        assert lam.std() > 1e-4  # moved away from uniform

    def test_converges(self):
        trainer, data = _trainer("afl", lr=0.3, rate=1.0,
                                 drfa_gamma=0.1)
        server, _, _ = _run(trainer, 25)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.45


class TestDRFA:
    @pytest.mark.parametrize("inner", ["fedavg", "fedgate", "scaffold"])
    def test_wraps_inner(self, inner):
        trainer, _ = _trainer(inner, drfa=True)
        assert isinstance(trainer.algorithm, DRFA)
        assert trainer.algorithm.inner.name == inner

    def test_rejects_bad_inner(self):
        with pytest.raises(ValueError, match="DRFA wraps"):
            _trainer("qffl", drfa=True)

    def test_lambda_init_proportional_to_sizes(self):
        trainer, data = _trainer("fedavg", drfa=True)
        server, clients = trainer.init_state(jax.random.key(0))
        lam = np.asarray(server.aux["lambda"])
        sizes = np.asarray(trainer.data.sizes, np.float32)
        np.testing.assert_allclose(lam, sizes / sizes.sum(), rtol=1e-5)

    def test_round_runs_and_lambda_updates(self):
        trainer, _ = _trainer("fedavg", drfa=True, drfa_gamma=0.5)
        server, clients = trainer.init_state(jax.random.key(1))
        lam0 = np.asarray(server.aux["lambda"])
        server, clients, metrics = trainer.run_round(server, clients)
        lam1 = np.asarray(server.aux["lambda"])
        assert not np.allclose(lam0, lam1)
        assert lam1.sum() == pytest.approx(1.0, abs=1e-5)
        # kth_avg snapshot is populated (non-zero)
        kth_norm = sum(float(jnp.abs(x).sum())
                       for x in jax.tree.leaves(server.aux["kth_avg"]))
        assert kth_norm > 0

    def test_uniform_sampling_by_default(self):
        """Reference parity: the DRFA loop samples uniformly
        (drfa.py:71,216), so the default participation hook defers to the
        engine (returns None)."""
        trainer, _ = _trainer("fedavg", drfa=True, num_clients=8, rate=0.25)
        alg = trainer.algorithm
        out = alg.participation(jax.random.key(0), 8, 2, jnp.asarray(1),
                                {"lambda": jnp.ones(8) / 8})
        assert out is None

    def test_gamma_decays_per_round(self):
        trainer, _ = _trainer("fedavg", drfa=True, drfa_gamma=0.1)
        server, clients = trainer.init_state(jax.random.key(0))
        assert float(server.aux["gamma"]) == pytest.approx(0.1)
        server, clients, _ = trainer.run_round(server, clients)
        assert float(server.aux["gamma"]) == pytest.approx(0.09)
        server, clients, _ = trainer.run_round(server, clients)
        assert float(server.aux["gamma"]) == pytest.approx(0.081)

    def test_lambda_weighted_sampling_option(self):
        """Paper-faithful sampling (drfa_lambda_sampling=True): larger
        lambda sampled more often."""
        trainer, _ = _trainer("fedavg", drfa=True, num_clients=8,
                              rate=0.25, drfa_lambda_sampling=True)
        alg = trainer.algorithm
        lam = jnp.asarray([0.6, 0.2, 0.05, 0.05, 0.025, 0.025, 0.025,
                           0.025])
        counts = np.zeros(8)
        for s in range(300):
            idx = alg.participation(jax.random.key(s), 8, 2,
                                    jnp.asarray(1), {"lambda": lam})
            counts[np.asarray(idx)] += 1
        assert counts[0] > counts[2] > 0 or counts[0] > 50
        assert counts[0] == max(counts)

    def test_converges(self):
        trainer, data = _trainer("fedavg", drfa=True, lr=0.3,
                                 drfa_gamma=0.05, local_step=5)
        server, _, _ = _run(trainer, 20)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.45

    def test_drfa_scaffold_converges(self):
        trainer, data = _trainer("scaffold", drfa=True, lr=0.3,
                                 drfa_gamma=0.05, local_step=5)
        server, _, _ = _run(trainer, 15)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.4
