"""LR schedule compiler tests, cross-checked against the reference's
closure-based scheduler (optimizers/learning.py) run directly."""
import sys
import types

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.config import LRConfig, OptimConfig, TrainConfig
from fedtorch_tpu.core.schedule import compile_schedule, lr_at
from fedtorch_tpu.core.sync import define_sync_freq

sys.path.insert(0, "/root/reference")


def _ref_scheduler(**kw):
    """Build the reference scheduler from a minimal args namespace."""
    pytest.importorskip(
        "fedtorch",
        reason="reference checkout not mounted at /root/reference")
    from fedtorch.components.optimizers.learning import get_lr_scheduler
    args = types.SimpleNamespace(
        lr_schedule_scheme=None, lr_change_epochs=None, lr_fields=None,
        lr_scale_indicators=None, lr_warmup=False, lr_warmup_epochs=5,
        lr_decay=10.0, learning_rate=0.1, init_warmup_lr=0.1,
        num_epochs=30, lr_gamma=None, lr_mu=None, lr_alpha=None,
        lr_onecycle_low=0.15, lr_onecycle_high=3.0,
        lr_onecycle_extra_low=0.0015, lr_onecycle_num_epoch=46)
    for k, v in kw.items():
        setattr(args, k, v)
    return get_lr_scheduler(args), args


def test_strict_matches_reference():
    ref, args = _ref_scheduler(
        lr_schedule_scheme="strict", lr_change_epochs="10,20",
        lr_fields="0.1,0.1/0.01,0.01/0.001,0.001",
        lr_scale_indicators="0,0,0", num_epochs=30)
    sched = compile_schedule(
        LRConfig(schedule_scheme="strict", lr_change_epochs="10,20",
                 lr_fields="0.1,0.1/0.01,0.01/0.001,0.001",
                 lr_scale_indicators="0,0,0"),
        OptimConfig(lr=0.1), num_epochs=30)
    for e in [0.0, 0.5, 9.99, 10.0, 15.7, 20.0, 29.9]:
        assert float(lr_at(sched, e)) == pytest.approx(ref(e), rel=1e-6), e


def test_multistep_matches_reference():
    ref, args = _ref_scheduler(
        lr_schedule_scheme="custom_multistep", lr_change_epochs="15,25",
        num_epochs=40)
    sched = compile_schedule(
        LRConfig(schedule_scheme="custom_multistep", lr_change_epochs="15,25",
                 decay=10.0),
        OptimConfig(lr=0.1), num_epochs=40)
    for e in [0.0, 7.3, 14.99, 15.0, 20.0, 25.0, 39.5]:
        assert float(lr_at(sched, e)) == pytest.approx(ref(e), rel=1e-6), e


def test_multistep_warmup_matches_reference():
    """Warmup PREPENDS a field: the base-LR plateau must survive from
    warmup end (5) to the first change epoch (15) — learning.py:139-154.
    Scale-up makes warmup ramp unscaled-lr -> scaled base lr."""
    ref, args = _ref_scheduler(
        lr_schedule_scheme="custom_multistep", lr_change_epochs="15,25",
        lr_warmup=True, lr_warmup_epochs=5, init_warmup_lr=0.01,
        learning_rate=0.1, num_epochs=40)
    sched = compile_schedule(
        LRConfig(schedule_scheme="custom_multistep",
                 lr_change_epochs="15,25", decay=10.0, warmup=True,
                 warmup_epochs=5, scaleup=True, scaleup_factor=10.0),
        OptimConfig(lr=0.01), num_epochs=40)
    for e in [0.0, 2.5, 4.99, 5.0, 9.0, 14.99, 15.0, 20.0, 25.0, 39.5]:
        assert float(lr_at(sched, e)) == pytest.approx(ref(e),
                                                       rel=1e-5), e


def test_multistep_warmup_no_change_epochs_matches_reference():
    """lr_change_epochs=None + warmup: two fields (ramp, then constant);
    the LR must NOT keep increasing past warmup end."""
    ref, args = _ref_scheduler(
        lr_schedule_scheme="custom_multistep", lr_change_epochs=None,
        lr_warmup=True, lr_warmup_epochs=5, init_warmup_lr=0.02,
        learning_rate=0.2, num_epochs=30)
    sched = compile_schedule(
        LRConfig(schedule_scheme="custom_multistep", decay=10.0,
                 warmup=True, warmup_epochs=5, scaleup=True,
                 scaleup_factor=10.0),
        OptimConfig(lr=0.02), num_epochs=30)
    for e in [0.0, 2.5, 5.0, 10.0, 29.9]:
        assert float(lr_at(sched, e)) == pytest.approx(ref(e),
                                                       rel=1e-5), e
    # the plateau holds the scaled base LR, no post-warmup growth
    assert float(lr_at(sched, 29.0)) == pytest.approx(0.2, rel=1e-5)


def test_multistep_warmup_overlapping_fields_first_match():
    """warmup_epochs (10) past the first change epoch (5) produces
    OVERLAPPING fields; the reference's sequential fall_in returns the
    first match, never a sum of matches."""
    ref, args = _ref_scheduler(
        lr_schedule_scheme="custom_multistep", lr_change_epochs="5,15",
        lr_warmup=True, lr_warmup_epochs=10, init_warmup_lr=0.1,
        learning_rate=0.1, num_epochs=30)
    sched = compile_schedule(
        LRConfig(schedule_scheme="custom_multistep",
                 lr_change_epochs="5,15", decay=10.0, warmup=True,
                 warmup_epochs=10),
        OptimConfig(lr=0.1), num_epochs=30)
    for e in [0.0, 4.0, 6.0, 9.5, 10.0, 14.9, 15.0, 29.9]:
        assert float(lr_at(sched, e)) == pytest.approx(ref(e),
                                                       rel=1e-5), e
    # in the overlap window first-match = warmup field, and the value
    # must never exceed the larger of the overlapping fields
    assert float(lr_at(sched, 7.0)) == pytest.approx(0.1, rel=1e-5)


def test_onecycle_matches_reference():
    ref, args = _ref_scheduler(lr_schedule_scheme="custom_one_cycle",
                               num_epochs=60)
    sched = compile_schedule(
        LRConfig(schedule_scheme="custom_one_cycle"),
        OptimConfig(lr=0.1), num_epochs=60)
    for e in [0.0, 11.5, 23.0, 34.5, 46.0, 59.0]:
        assert float(lr_at(sched, e)) == pytest.approx(ref(e), rel=1e-5), e


def test_convex_decay_matches_reference():
    ref, args = _ref_scheduler(
        lr_schedule_scheme="custom_convex_decay", lr_gamma=1.0, lr_mu=0.5,
        lr_alpha=1.0, num_epochs=20)
    sched = compile_schedule(
        LRConfig(schedule_scheme="custom_convex_decay", gamma=1.0, mu=0.5,
                 alpha=1.0),
        OptimConfig(lr=0.1), num_epochs=20)
    for e in [0.0, 1.0, 5.5, 19.9]:
        assert float(lr_at(sched, e)) == pytest.approx(ref(e), rel=1e-5), e


def test_constant_default():
    sched = compile_schedule(LRConfig(), OptimConfig(lr=0.03), num_epochs=10)
    assert float(lr_at(sched, 0.0)) == pytest.approx(0.03)
    assert float(lr_at(sched, 9.99)) == pytest.approx(0.03)
    # saturates past the end rather than returning 0/None
    assert float(lr_at(sched, 10.5)) == pytest.approx(0.03)


def test_jit_and_scan_evaluable():
    sched = compile_schedule(
        LRConfig(schedule_scheme="custom_multistep", lr_change_epochs="5",
                 decay=10.0),
        OptimConfig(lr=0.1), num_epochs=10)

    def body(carry, e):
        return carry, lr_at(sched, e)

    _, lrs = jax.lax.scan(body, 0, jnp.asarray([0.0, 4.9, 5.0, 9.9]))
    np.testing.assert_allclose(np.asarray(lrs), [0.1, 0.1, 0.01, 0.01],
                               rtol=1e-5)


class TestSyncScheme:
    def _ref(self, **kw):
        pytest.importorskip(
            "fedtorch",
            reason="reference checkout not mounted at /root/reference")
        from fedtorch.comms.algorithms.distributed import define_sync_freq \
            as ref_fn
        defaults = dict(num_epochs=10, local_step=4,
                        local_step_warmup_type=None,
                        local_step_warmup_period=None,
                        turn_on_local_step_from=None,
                        turn_off_local_step_from=None,
                        warmup_per_intervals=False, lr_change_epochs=None)
        defaults.update(kw)
        return ref_fn(**defaults), define_sync_freq(**defaults)

    def test_plain(self):
        ref, ours = self._ref()
        assert ref == ours

    @pytest.mark.parametrize("warmup", ["exp", "linear", "constant"])
    def test_warmup_types(self, warmup):
        ref, ours = self._ref(local_step_warmup_type=warmup,
                              local_step_warmup_period=6)
        assert ref == ours

    def test_turn_off(self):
        ref, ours = self._ref(lr_change_epochs="5",
                              turn_off_local_step_from=5)
        assert ref == ours

    def test_turn_on(self):
        ref, ours = self._ref(lr_change_epochs="5",
                              turn_on_local_step_from=5)
        assert ref == ours

    def test_warmup_per_interval(self):
        ref, ours = self._ref(lr_change_epochs="6", warmup_per_intervals=True,
                              local_step_warmup_type="linear",
                              local_step_warmup_period=3)
        assert ref == ours


def test_config_finalize_derivations():
    from fedtorch_tpu.config import ExperimentConfig, FederatedConfig
    cfg = ExperimentConfig(
        federated=FederatedConfig(federated=True, num_comms=20,
                                  num_epochs_per_comm=2,
                                  online_client_rate=0.5,
                                  algorithm="afl")).finalize()
    # num_epochs = 2*20*0.5 (parameters.py:248)
    assert cfg.train.num_epochs == 20
    # afl coercions (parameters.py:249-251)
    assert cfg.federated.sync_type == "local_step"
    assert cfg.train.local_step == 1

    cfg2 = ExperimentConfig(
        federated=FederatedConfig(federated=True, algorithm="apfl")).finalize()
    assert cfg2.federated.personal  # parameters.py:257-259

    with pytest.raises(ValueError):
        ExperimentConfig(federated=FederatedConfig(
            federated=True, quantized=True, compressed=True)).finalize()
