"""Unit tests for in-graph ops: quantize, top-k, simplex projection.

Where a torch reference implementation exists in /root/reference
(flow_utils.py), we cross-check numerics against it directly (torch-cpu is
available in the test image) — this validates semantic parity without
copying code.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.ops import (
    compress, decompress, dequantize, project_simplex, project_simplex_floor,
    quantize, quantize_dequantize, topk_roundtrip,
)


class TestQuantize:
    def test_roundtrip_error_bounded(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(1000).astype(np.float32))
        for bits in (8, 16):
            q, info = quantize(x, num_bits=bits, adaptive=True)
            xr = dequantize(q, info)
            # rounding gives scale/2; zero-point truncation can push edge
            # values past the clip range for up to one extra scale unit
            assert float(jnp.max(jnp.abs(xr - x))) <= float(info.scale) * 1.51 + 1e-6

    def test_dtypes(self):
        x = jnp.linspace(-1, 1, 64)
        q8, _ = quantize(x, num_bits=8)
        q16, _ = quantize(x, num_bits=16)
        assert q8.dtype == jnp.int8 and q16.dtype == jnp.int16

    def test_constant_tensor_scale_floor(self):
        x = jnp.full((32,), 3.14)
        q, info = quantize(x, num_bits=8)
        assert float(info.scale) == pytest.approx(0.001)
        xr = dequantize(q, info)
        np.testing.assert_allclose(np.asarray(xr), np.asarray(x), atol=1e-3)

    def test_matches_torch_reference(self):
        torch = pytest.importorskip("torch")
        import sys
        sys.path.insert(0, "/root/reference")
        pytest.importorskip(
            "fedtorch",
            reason="reference checkout not mounted at /root/reference")
        from fedtorch.comms.utils.flow_utils import (
            quantize_tensor, dequantize_tensor)
        rng = np.random.RandomState(42)
        x_np = rng.randn(257).astype(np.float32)
        q_t, info_t = quantize_tensor(torch.tensor(x_np), num_bits=8,
                                      adaptive=True)
        x_t = dequantize_tensor(q_t, info_t).numpy()
        x_j = np.asarray(quantize_dequantize(jnp.asarray(x_np), num_bits=8))
        np.testing.assert_allclose(x_j, x_t, atol=2e-2, rtol=0)
        # bulk agreement: identical reconstruction for almost all elements
        # (round-half ties may differ at fp boundaries)
        frac_equal = np.mean(np.abs(x_j - x_t) < 1e-6)
        assert frac_equal > 0.98

    def test_jittable(self):
        f = jax.jit(lambda x: quantize_dequantize(x, 8))
        x = jnp.linspace(-2, 2, 128)
        # jit fusion may flip round-half ties at bin boundaries; agree to
        # within one quantization bin
        np.testing.assert_allclose(np.asarray(f(x)),
                                   np.asarray(quantize_dequantize(x, 8)),
                                   atol=4.0 / 255 + 1e-6)


class TestTopK:
    def test_keeps_largest(self):
        x = jnp.asarray([0.1, -5.0, 0.2, 3.0, -0.05, 0.0, 1.0, -2.0])
        sp = compress(x, ratio=1.0)  # k = 8*1/2 = 4
        assert sp.values.shape == (4,)
        dense = decompress(sp)
        np.testing.assert_allclose(
            np.asarray(dense),
            np.asarray([0.0, -5.0, 0.0, 3.0, 0.0, 0.0, 1.0, -2.0]))

    def test_k_rule_matches_reference(self):
        # k = int(n*r/2), flow_utils.py:221
        x = jnp.arange(100, dtype=jnp.float32)
        sp = compress(x, ratio=0.5)
        assert sp.values.shape == (25,)

    def test_ratio_too_low_raises(self):
        with pytest.raises(ValueError):
            compress(jnp.arange(3, dtype=jnp.float32), ratio=0.1)

    def test_roundtrip_preserves_shape(self):
        x = jnp.ones((4, 8))
        y = topk_roundtrip(x, ratio=0.5)
        assert y.shape == x.shape

    def test_jit_static_k(self):
        f = jax.jit(lambda x: topk_roundtrip(x, 0.5))
        x = jnp.asarray(np.random.RandomState(1).randn(64).astype(np.float32))
        y = f(x)
        assert int(jnp.sum(y != 0)) == 16


class TestSimplex:
    def test_already_on_simplex(self):
        v = jnp.asarray([0.2, 0.3, 0.5])
        np.testing.assert_allclose(np.asarray(project_simplex(v)),
                                   np.asarray(v), atol=1e-6)

    def test_sums_to_one_nonneg(self):
        rng = np.random.RandomState(3)
        for _ in range(5):
            v = jnp.asarray(rng.randn(50).astype(np.float32) * 3)
            w = project_simplex(v)
            assert float(jnp.sum(w)) == pytest.approx(1.0, abs=1e-5)
            assert float(jnp.min(w)) >= 0.0

    def test_matches_reference_numpy_sort(self):
        import sys
        sys.path.insert(0, "/root/reference")
        pytest.importorskip(
            "fedtorch",
            reason="reference checkout not mounted at /root/reference")
        from fedtorch.comms.utils.flow_utils import projection_simplex_sort
        rng = np.random.RandomState(7)
        v = rng.randn(30).astype(np.float64)
        w_ref = projection_simplex_sort(v.copy())
        w = np.asarray(project_simplex(jnp.asarray(v, jnp.float32)))
        np.testing.assert_allclose(w, w_ref, atol=1e-5)

    def test_floor(self):
        v = jnp.asarray([10.0, -10.0, -10.0, -10.0])
        w = project_simplex_floor(v, floor=1e-3)
        # after the single renormalization the floor holds up to the
        # normalizer (reference drfa.py:246-250 semantics)
        assert float(jnp.min(w)) >= 1e-3 / (1.0 + 4 * 1e-3) - 1e-9
        assert float(jnp.sum(w)) == pytest.approx(1.0, abs=1e-5)

    def test_jittable(self):
        f = jax.jit(project_simplex)
        v = jnp.asarray([3.0, 1.0, -2.0])
        w = f(v)
        assert float(jnp.sum(w)) == pytest.approx(1.0, abs=1e-6)
