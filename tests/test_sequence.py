"""Sequence parallelism (ring + ulysses attention) correctness tests."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from fedtorch_tpu.parallel.sequence import (
    reference_attention, ring_attention, ulysses_attention,
)

# both strategies execute inside jax.shard_map; jax releases that only
# expose jax.experimental.shard_map raise AttributeError before any
# attention math runs. A version skip (not a red baseline) so real
# regressions stay visible. The argument-validation tests below raise
# BEFORE shard_map and stay un-marked.
requires_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax does not expose the public jax.shard_map API "
           "(only jax.experimental.shard_map); the sequence-parallel "
           "strategies need it")


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("sp",))


def _qkv(b=2, s=32, h=4, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape) for k in ks)


@requires_shard_map
@pytest.mark.parametrize("n_shards", [1, 2, 8])
def test_matches_dense_attention(n_shards):
    q, k, v = _qkv()
    out = ring_attention(q, k, v, _mesh(n_shards))
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@requires_shard_map
@pytest.mark.parametrize("n_shards", [2, 8])
def test_causal_matches_dense(n_shards):
    q, k, v = _qkv(seed=3)
    out = ring_attention(q, k, v, _mesh(n_shards), causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@requires_shard_map
def test_long_sequence_sharded():
    """A sequence too big to be comfortable dense still runs sharded."""
    q, k, v = _qkv(b=1, s=1024, h=2, d=8, seed=5)
    out = ring_attention(q, k, v, _mesh(8), causal=True)
    assert out.shape == (1, 1024, 2, 8)
    assert bool(jnp.all(jnp.isfinite(out)))
    # spot-check the first 64 positions against dense
    ref = reference_attention(q[:, :64], k[:, :64], v[:, :64], causal=True)
    np.testing.assert_allclose(np.asarray(out[:, :64]), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@requires_shard_map
def test_jit_compatible():
    mesh = _mesh(2)
    q, k, v = _qkv(s=16)
    f = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(reference_attention(q, k, v)),
                               atol=2e-5, rtol=2e-5)


class TestRingFlashBlocks:
    """block_impl='flash': each ring step through the flash kernel,
    pieces merged by logsumexp weighting (parallel/sequence.py
    _ring_flash_local)."""

    @requires_shard_map
    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_oracle(self, n_shards, causal):
        q, k, v = _qkv(s=64, seed=7)
        out = ring_attention(q, k, v, _mesh(n_shards), causal=causal,
                             block_impl="flash")
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @requires_shard_map
    def test_matches_dense_block_impl(self):
        q, k, v = _qkv(s=64, seed=9)
        a = ring_attention(q, k, v, _mesh(4), causal=True,
                           block_impl="flash")
        b = ring_attention(q, k, v, _mesh(4), causal=True,
                           block_impl="dense")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)

    @requires_shard_map
    def test_gradients_match_oracle(self):
        """The lse joint VJP composes with the sharded merge: grads
        through the flash ring == grads through dense attention."""
        q, k, v = _qkv(s=64, seed=11)
        mesh = _mesh(8)
        gf = jax.grad(lambda q: jnp.sum(ring_attention(
            q, k, v, mesh, causal=True, block_impl="flash") ** 2))(q)
        gr = jax.grad(lambda q: jnp.sum(reference_attention(
            q, k, v, causal=True) ** 2))(q)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-5, rtol=5e-4)

    def test_rejects_unknown_impl(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="block_impl"):
            ring_attention(q, k, v, _mesh(2), block_impl="sparse")

    @requires_shard_map
    @pytest.mark.parametrize("strategy", ["ring", "ulysses"])
    def test_real_kernel_traces_under_shard_map_vma(self, strategy,
                                                    monkeypatch):
        """shard_map's check_vma requires pallas_call outputs to
        declare their varying mesh axes; the kernel propagates the
        inputs' vma onto out_shape. Off-TPU the flash call falls back
        to the XLA oracle, so this combination first fired on the real
        chip (round 5, SEQPAR_TPU_PROBE.json) — TRACING the real
        pallas path here (no execution) pins the check on CPU."""
        import fedtorch_tpu.ops.pallas.flash_attention as fa
        from fedtorch_tpu.parallel.sequence import ulysses_attention

        monkeypatch.setattr(fa, "on_tpu", lambda: True)
        q, k, v = _qkv(s=64, seed=13)
        mesh = _mesh(4)
        fn = (ring_attention if strategy == "ring"
              else ulysses_attention)
        jax.jit(lambda q, k, v: fn(
            q, k, v, mesh, causal=True,
            block_impl="flash")).trace(q, k, v)


class TestUlysses:
    """All-to-all (head-parallel) strategy: must agree with dense AND
    with the ring strategy on identical inputs."""

    @requires_shard_map
    @pytest.mark.parametrize("n_shards", [1, 2, 4])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, n_shards, causal):
        q, k, v = _qkv(seed=7)
        out = ulysses_attention(q, k, v, _mesh(n_shards), causal=causal)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @requires_shard_map
    def test_matches_ring(self):
        q, k, v = _qkv(b=1, s=64, h=8, d=8, seed=9)
        ring = ring_attention(q, k, v, _mesh(8), causal=True)
        uly = ulysses_attention(q, k, v, _mesh(8), causal=True)
        np.testing.assert_allclose(np.asarray(uly), np.asarray(ring),
                                   atol=2e-5, rtol=2e-5)

    @requires_shard_map
    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_local_matches_dense(self, causal):
        """block_impl='flash': the local full-sequence attention runs
        the flash kernel between the two all-to-alls — exact."""
        q, k, v = _qkv(s=64, seed=13)
        out = ulysses_attention(q, k, v, _mesh(4), causal=causal,
                                block_impl="flash")
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @requires_shard_map
    def test_flash_local_gradients_match_oracle(self):
        """The flash custom VJP composed with the two all-to-alls under
        shard_map: gradients == dense attention's."""
        q, k, v = _qkv(s=64, seed=15)
        mesh = _mesh(4)
        gf = jax.grad(lambda q: jnp.sum(ulysses_attention(
            q, k, v, mesh, causal=True, block_impl="flash") ** 2))(q)
        gr = jax.grad(lambda q: jnp.sum(reference_attention(
            q, k, v, causal=True) ** 2))(q)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-5, rtol=5e-4)

    def test_rejects_unknown_block_impl(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="block_impl"):
            ulysses_attention(q, k, v, _mesh(2), block_impl="sparse")

    def test_rejects_indivisible_heads(self):
        q, k, v = _qkv(h=4)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, _mesh(8))

    @requires_shard_map
    def test_jit_compatible(self):
        mesh = _mesh(4)
        q, k, v = _qkv(s=16, h=4)
        f = jax.jit(lambda a, b, c: ulysses_attention(
            a, b, c, mesh, causal=True))
        np.testing.assert_allclose(
            np.asarray(f(q, k, v)),
            np.asarray(reference_attention(q, k, v, causal=True)),
            atol=2e-5, rtol=2e-5)


@requires_shard_map
def test_sequence_parallel_training_step():
    """Long-context TRAINING, not just forward: optimizer steps through
    long_context_apply (ring + flash blocks) on the 8-shard mesh track
    dense-attention training exactly — same losses, decreasing."""
    import optax
    from fedtorch_tpu.models.transformer import TransformerLM, \
        long_context_apply

    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh(8)
    model = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                          num_layers=1, max_len=64)
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, 32)
    tgts = jnp.roll(toks, -1, axis=1)
    params = model.init(jax.random.key(0), toks)["params"]
    # training placement: params/tokens replicated over the SP mesh so
    # residual adds mix mesh-resident activations consistently
    rep = NamedSharding(mesh, P())
    params = jax.device_put(params, rep)
    toks, tgts = jax.device_put(toks, rep), jax.device_put(tgts, rep)

    def nll(logits):
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, tgts[..., None],
                                             axis=-1))

    def train(loss_fn, params, steps=3):
        opt = optax.sgd(0.5)
        state = opt.init(params)
        losses = []
        for _ in range(steps):
            loss, g = jax.value_and_grad(loss_fn)(params)
            upd, state = opt.update(g, state)
            params = optax.apply_updates(params, upd)
            losses.append(float(loss))
        return losses

    sp_losses = train(lambda p: nll(long_context_apply(
        model, p, toks, mesh, strategy="ring", block_impl="flash")),
        params)
    dense_losses = train(lambda p: nll(model.apply({"params": p}, toks)),
                         params)
    np.testing.assert_allclose(sp_losses, dense_losses, rtol=1e-4)
    assert sp_losses[-1] < sp_losses[0]


@requires_shard_map
def test_long_context_apply_ulysses_flash_matches_dense():
    """block_impl='flash' under ulysses runs the LOCAL head-slice
    attention through the flash kernel — same logits."""
    from fedtorch_tpu.models.transformer import TransformerLM, \
        long_context_apply
    model = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                          num_layers=1, max_len=64)
    toks = jax.random.randint(jax.random.key(1), (2, 64), 0, 32)
    params = model.init(jax.random.key(0), toks)["params"]
    ref = model.apply({"params": params}, toks)
    out = long_context_apply(model, params, toks, _mesh(2),
                             strategy="ulysses", block_impl="flash")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@requires_shard_map
def test_long_context_apply_strategies_agree():
    """The transformer forward must be identical under both
    sequence-parallel strategies and the dense baseline."""
    from fedtorch_tpu.models.transformer import TransformerLM, \
        long_context_apply

    model = TransformerLM(vocab_size=64, d_model=32, num_heads=8,
                          num_layers=2, max_len=128)
    toks = jax.random.randint(jax.random.key(2), (2, 128), 0, 64)
    params = model.init(jax.random.key(0), toks)["params"]
    dense = model.apply({"params": params}, toks)
    mesh = _mesh(8)
    for strategy in ("ring", "ulysses"):
        out = long_context_apply(model, params, toks, mesh,
                                 strategy=strategy)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=3e-4, rtol=3e-4, err_msg=strategy)
