"""Ring attention (sequence parallelism) correctness tests."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from fedtorch_tpu.parallel.sequence import (
    reference_attention, ring_attention,
)


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("sp",))


def _qkv(b=2, s=32, h=4, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape) for k in ks)


@pytest.mark.parametrize("n_shards", [1, 2, 8])
def test_matches_dense_attention(n_shards):
    q, k, v = _qkv()
    out = ring_attention(q, k, v, _mesh(n_shards))
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("n_shards", [2, 8])
def test_causal_matches_dense(n_shards):
    q, k, v = _qkv(seed=3)
    out = ring_attention(q, k, v, _mesh(n_shards), causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_long_sequence_sharded():
    """A sequence too big to be comfortable dense still runs sharded."""
    q, k, v = _qkv(b=1, s=1024, h=2, d=8, seed=5)
    out = ring_attention(q, k, v, _mesh(8), causal=True)
    assert out.shape == (1, 1024, 2, 8)
    assert bool(jnp.all(jnp.isfinite(out)))
    # spot-check the first 64 positions against dense
    ref = reference_attention(q[:, :64], k[:, :64], v[:, :64], causal=True)
    np.testing.assert_allclose(np.asarray(out[:, :64]), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_jit_compatible():
    mesh = _mesh(2)
    q, k, v = _qkv(s=16)
    f = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(reference_attention(q, k, v)),
                               atol=2e-5, rtol=2e-5)
