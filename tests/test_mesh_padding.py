"""Padded client axis: any num_clients must use ALL mesh devices.

The reference maps clients to MPI processes 1:1 (utils/topology.py:57-114)
so every client count trivially 'fits'; on a TPU mesh the client axis must
shard evenly, which the engine guarantees by padding with inert clients
(pad_client_axis) instead of idling devices. These tests pin:
 * no idle devices for awkward client counts (6, 10, 100 on 8 devices) —
   the north-star bench config is 100 clients;
 * padding is numerically inert: the training trajectory is identical to
   an unpadded single-device run;
 * per-client evaluation summaries exclude the padding tail.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, MeshConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.data.batching import pad_client_axis
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import (
    FederatedTrainer, evaluate_clients, make_mesh, padded_client_count,
)


def _cfg(num_clients, num_devices, rate=1.0):
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=12,
                        batch_size=8),
        federated=FederatedConfig(federated=True, num_clients=num_clients,
                                  online_client_rate=rate,
                                  algorithm="fedavg",
                                  sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.1, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        mesh=MeshConfig(num_devices=num_devices),
    ).finalize()


def _build(num_clients, num_devices, rate=1.0):
    cfg = _cfg(num_clients, num_devices, rate)
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=8)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)


@pytest.mark.parametrize("num_clients", [6, 10, 100])
def test_no_idle_devices(num_clients):
    """make_mesh must keep all 8 devices even when 8 does not divide C."""
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    t = _build(num_clients, num_devices=8, rate=0.5)
    assert t.mesh.devices.size == 8, (
        f"{num_clients} clients idled devices: mesh={t.mesh.devices.size}")
    assert t.padded_clients % 8 == 0
    assert t.padded_clients >= num_clients

    server, clients = t.init_state(jax.random.key(0))
    leaf = jax.tree.leaves(clients.params)[0]
    assert leaf.shape[0] == t.padded_clients
    assert len(leaf.sharding.device_set) == 8, leaf.sharding

    server, clients, metrics = t.run_round(server, clients)
    jax.block_until_ready(server.params)
    assert np.isfinite(float(metrics.train_loss.sum()))
    # metrics stay on the REAL client axis
    assert metrics.online_mask.shape == (num_clients,)


def test_padding_count_helper():
    mesh = make_mesh(MeshConfig(num_devices=8))
    assert padded_client_count(6, mesh) == 8
    assert padded_client_count(8, mesh) == 8
    assert padded_client_count(10, mesh) == 16
    assert padded_client_count(100, mesh) == 104


@pytest.mark.parametrize("num_clients", [6, 10])
def test_padding_numerically_inert(num_clients):
    """Same seed, same config: the padded 8-device run must reproduce the
    unpadded 1-device trajectory exactly (padding weight is zero)."""
    t1 = _build(num_clients, num_devices=1)
    t8 = _build(num_clients, num_devices=8)
    assert t1.padded_clients == num_clients  # 1 device: no padding
    assert t8.padded_clients % 8 == 0

    s1, c1 = t1.init_state(jax.random.key(7))
    s8, c8 = t8.init_state(jax.random.key(7))
    for _ in range(3):
        s1, c1, m1 = t1.run_round(s1, c1)
        s8, c8, m8 = t8.run_round(s8, c8)
    for a, b in zip(jax.tree.leaves(s1.params),
                    jax.tree.leaves(s8.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m1.train_loss),
                               np.asarray(m8.train_loss), atol=1e-5)


def test_partial_participation_never_selects_padding():
    """With rate<1 the sampled indices must stay inside the real range."""
    t = _build(10, num_devices=8, rate=0.3)
    server, clients = t.init_state(jax.random.key(3))
    for _ in range(5):
        server, clients, metrics = t.run_round(server, clients)
        mask = np.asarray(metrics.online_mask)
        assert mask.shape == (10,)
        assert mask.sum() == t.k_online
    # the padding tail of the client state never left its init value
    pad_epochs = np.asarray(clients.epoch)[10:]
    assert np.all(pad_epochs == 0.0)


def test_evaluate_clients_ignores_padding():
    """Cross-client summaries must not include the inert padding tail."""
    t = _build(6, num_devices=8)
    server, clients = t.init_state(jax.random.key(1))
    server, clients, _ = t.run_round(server, clients)
    losses, accs, summary = evaluate_clients(
        t.model, clients.params, t.data, batch_size=8, max_batches=2)
    assert losses.shape[0] == t.padded_clients
    real_accs = np.asarray(accs)[:6]
    assert summary["acc_worst"] == pytest.approx(float(real_accs.min()))
    assert summary["acc_best"] == pytest.approx(float(real_accs.max()))


def test_local_sgd_stop_criterion_unbiased_by_padding():
    """6 workers on 8 devices: the epoch-based stop must count only the
    real workers, not the never-advancing padding tail (which would make
    training overshoot the requested epoch count by padded/real)."""
    from fedtorch_tpu.data import generate_synthetic
    from fedtorch_tpu.parallel import build_local_sgd

    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=12,
                        batch_size=10),
        federated=FederatedConfig(federated=False, num_clients=6),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.2, weight_decay=0.0),
        train=TrainConfig(num_epochs=2, local_step=2),
        mesh=MeshConfig(num_devices=8),
    ).finalize()
    d = generate_synthetic(num_tasks=4, alpha=0.0, beta=0.0, num_dim=12)
    feats = np.concatenate(d.client_x)
    labels = np.concatenate(d.client_y)
    model = define_model(cfg, batch_size=10)
    trainer = build_local_sgd(cfg, model, feats, labels)
    assert trainer.padded_clients == 8 and trainer.num_clients == 6
    server, clients, history = trainer.fit(jax.random.key(0))
    real_epochs = np.asarray(clients.epoch)[:6]
    # every real worker finished ~2 epochs, with at most one extra round
    # of overshoot (rounds are local_step-sized)
    assert real_epochs.min() >= 2.0
    assert real_epochs.max() < 2.5
    assert np.all(np.asarray(clients.epoch)[6:] == 0.0)


def test_emnist_scale_client_count():
    """EMNIST-scale federation: 3383 clients (the reference's natural
    fed_emnist client count, federated_datasets.py) on the 8-device
    mesh at 1% participation. Pins that the padded layout, static-k
    sampling, and scatter-back stay correct and tractable at three
    orders of magnitude more clients than devices."""
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=12,
                        batch_size=8, synthetic_samples_per_client=16),
        federated=FederatedConfig(federated=True, num_clients=3383,
                                  online_client_rate=0.01,
                                  algorithm="fedavg",
                                  sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.1, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        mesh=MeshConfig(num_devices=8),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=8)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                               data.train)
    assert trainer.k_online == 33
    assert trainer.padded_clients % 8 == 0
    server, clients = trainer.init_state(jax.random.key(0))
    leaf = jax.tree.leaves(clients.params)[0]
    assert len(leaf.sharding.device_set) == 8
    server, clients, m = trainer.run_round(server, clients)
    mask = np.asarray(m.online_mask)
    assert int(mask.sum()) == 33
    # sampling never touches the padding tail
    assert mask[3383:].sum() == 0
    loss = float(m.train_loss.sum() / mask.sum())
    assert np.isfinite(loss)


def test_pad_client_axis_shapes():
    from fedtorch_tpu.data.batching import ClientData
    data = ClientData(x=jnp.ones((3, 5, 2)), y=jnp.ones((3, 5)),
                      sizes=jnp.asarray([5, 4, 3], jnp.int32))
    padded = pad_client_axis(data, 8)
    assert padded.x.shape == (8, 5, 2)
    assert padded.y.shape == (8, 5)
    assert list(np.asarray(padded.sizes)) == [5, 4, 3, 0, 0, 0, 0, 0]
    assert pad_client_axis(data, 3) is data
    with pytest.raises(ValueError):
        pad_client_axis(data, 2)
