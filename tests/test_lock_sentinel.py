"""Unit tests for the runtime lock-order sentinel — the dynamic half
of the FTH concurrency audit (docs/static_analysis.md "The runtime
half: the lock-order sentinel").

The sentinel must (a) turn a forced two-lock order inversion into a
violation raised at scope exit, (b) turn a re-entrant acquire — the
PR 10 injector self-deadlock shape — into an IMMEDIATE AssertionError
instead of a hang, (c) stay silent on consistently-ordered runs, and
(d) leave no trace after exit: the faults.new_lock factory hook is
restored and watched attributes are swapped back.
"""
import threading

import pytest

from fedtorch_tpu.telemetry import faults as tel_faults
from fedtorch_tpu.utils.lock_sentinel import (
    LockOrderSentinel, active_sentinel,
)


def test_clean_ordered_run_passes_and_records_edges():
    with LockOrderSentinel() as s:
        x = tel_faults.new_lock("X")
        y = tel_faults.new_lock("Y")
        for _ in range(3):
            with x:
                with y:
                    pass
        assert s.order_edges() == {"X": ["Y"]}
        s.assert_clean()
    # strict __exit__ already re-asserted clean; no violations recorded
    assert s.violations == []


def test_two_lock_inversion_raises_at_exit():
    """Thread A takes X->Y, thread B takes Y->X: the classic deadlock
    recipe. Serialized via events so the runs interleave without
    actually deadlocking — the sentinel must still flag the ORDER."""
    with pytest.raises(AssertionError, match="lock-order inversion"):
        with LockOrderSentinel() as s:
            x = tel_faults.new_lock("X")
            y = tel_faults.new_lock("Y")

            with x:
                with y:
                    pass

            def inverted():
                with y:
                    with x:
                        pass

            t = threading.Thread(target=inverted,
                                 name="sentinel-test-inverter")
            t.start()
            t.join()
            assert s.violations, "inversion not recorded"


def test_inversion_nonstrict_reports_via_assert_clean():
    with LockOrderSentinel(strict=False) as s:
        x = tel_faults.new_lock("X")
        y = tel_faults.new_lock("Y")
        with x:
            with y:
                pass

        def inverted():
            with y:
                with x:
                    pass

        t = threading.Thread(target=inverted,
                             name="sentinel-test-inverter")
        t.start()
        t.join()
    assert len(s.violations) == 1
    with pytest.raises(AssertionError, match="1 violation"):
        s.assert_clean()


def test_reentrant_acquire_raises_immediately():
    """The PR 10 self-deadlock shape: re-acquiring a held
    non-reentrant lock must raise NOW, not hang the process."""
    with LockOrderSentinel(strict=False) as s:
        m = tel_faults.new_lock("W._mutex")
        with m:
            with pytest.raises(AssertionError, match="re-entrant"):
                m.acquire()
        assert any("PR 10" in v for v in s.violations)


def test_watch_wraps_and_restores_existing_locks():
    class Holder:
        def __init__(self):
            self._lock = threading.Lock()
            self._rlock = threading.RLock()

    h = Holder()
    original = h._lock
    with LockOrderSentinel() as s:
        s.watch(h, "_lock", "_rlock")
        assert h._lock is not original
        with h._lock:
            pass
        # RLocks are re-entrant by contract: no false positive
        with h._rlock:
            with h._rlock:
                pass
    assert h._lock is original
    assert s.violations == []


def test_hook_and_active_sentinel_restored_after_exit():
    assert active_sentinel() is None
    with LockOrderSentinel() as s:
        assert active_sentinel() is s
        wrapped = tel_faults.new_lock("inner")
        assert wrapped.name == "inner"
    assert active_sentinel() is None
    # hook restored: new_lock now returns a plain threading.Lock
    plain = tel_faults.new_lock("after")
    assert type(plain) is type(threading.Lock())
    # wrappers that outlive the sentinel degrade to pass-through
    with wrapped:
        pass
    assert s.violations == []


def test_nested_sentinels_restore_outer_hook():
    with LockOrderSentinel() as outer:
        with LockOrderSentinel() as inner:
            assert active_sentinel() is inner
        assert active_sentinel() is outer
        lk = tel_faults.new_lock("back-to-outer")
        assert lk._sentinel is outer
