"""Pod-scale round programs (ISSUE 20): the client-axis sharded cells.

The engine-wide bars, on the 8-device XLA-forced CPU mesh every tier-1
run carries:

* **S-shard parity** — every legal (source x dispatch x vmap) cell at
  ``mesh.client_shards`` S in {2, 4} is BITWISE-identical per round to
  its armed 1-shard twin (the S=1 2-D mesh running the same grouped
  hierarchical aggregation seam), and traces exactly once;
* **degraded-pod resume** — a checkpoint taken at S=4 restored onto
  S=2 continues the S=1 trajectory bitwise (the hierarchical sum's
  association is a function of k alone, never of S);
* **named refusals** — each illegal sharded composition (fused
  execution, non-dividing cohort, robust rules, cohort stats,
  uncertified algorithms, shard gather mode, non-dividing commit
  buffer) raises ONE ValueError naming the cell from validate_cell,
  including the relocated fused-x-multi-device refusal with its exact
  message (ISSUE 20 satellite: fusion.py no longer owns it);
* **torn-shard recovery** — under per-host sharded packing a torn
  ``MmapClientStore`` shard escalates through the
  'stream.gather' -> 'stream.producer' chain NAMING the owning
  host/shard, and after the file heals the run recovers bitwise.
"""
import re

import jax
import numpy as np
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
    MeshConfig, ModelConfig, OptimConfig, TelemetryConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.parallel.mesh import (
    local_cohort_rows, mesh_client_shards, replicate, shard_clients,
)
from fedtorch_tpu.parallel.podscale import (
    cohort_group_count, cohort_hierarchical_sum,
)
from fedtorch_tpu.parallel.round_program import (
    DISPATCHES, SOURCES, illegal_reason,
)
from fedtorch_tpu.robustness import HostSeamError
from fedtorch_tpu.utils.tracing import RecompilationSentinel

SHARD_COUNTS = (1, 2, 4)
VMAP_CELLS = [(s, d) for s in SOURCES for d in DISPATCHES]


def make_cfg(source, dispatch, shards, *, num_clients=8, rate=0.5,
             store="ram", store_dir="", fault_kw=None, telemetry_kw=None,
             algorithm="fedavg", gather_mode=None, buffer_size=4,
             fusion="vmap"):
    plane = "stream" if source == "feed" else "device"
    sync_mode = "async" if dispatch == "commit" else "sync"
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=16,
                        batch_size=8, synthetic_alpha=0.5,
                        synthetic_beta=0.5, data_plane=plane,
                        store=store, store_dir=store_dir),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients,
            online_client_rate=rate, algorithm=algorithm,
            sync_type="local_step", sync_mode=sync_mode,
            async_buffer_size=buffer_size, async_concurrency=4),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.3, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        mesh=MeshConfig(client_shards=shards, client_fusion=fusion),
        fault=FaultConfig(**(fault_kw or {})),
        telemetry=TelemetryConfig(**(telemetry_kw or {})),
    ).finalize()


def build_trainer(cfg, data=None):
    data = data if data is not None else build_federated_data(cfg).train
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    if cfg.federated.sync_mode == "async":
        from fedtorch_tpu.async_plane import AsyncFederatedTrainer
        return AsyncFederatedTrainer(cfg, model, make_algorithm(cfg),
                                     data)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data)


def run_cell(trainer, dispatch, rounds=2, seed=3):
    server, clients = trainer.init_state(jax.random.key(seed))
    metrics = []
    if dispatch == "scan":
        server, clients, ms = trainer.run_rounds(server, clients,
                                                 rounds)
        metrics.append(jax.tree.map(np.asarray, ms))
    else:
        for _ in range(rounds):
            server, clients, m = trainer.run_round(server, clients)
            metrics.append(jax.tree.map(np.asarray, m))
    trainer.invalidate_stream()
    return (jax.tree.map(np.asarray, (server.params, server.aux)),
            jax.tree.map(np.asarray, clients), metrics)


def cell_trace_name(trainer, source, dispatch, rounds=2):
    if dispatch == "round":
        return trainer.trace_name if source == "resident" \
            else trainer.stream_trace_name
    if dispatch == "commit":
        return trainer.commit_trace_name if source == "resident" \
            else trainer.commit_stream_trace_name
    suffix = "" if source == "resident" else "_stream"
    return (f"federated.rounds{suffix}"
            f"[{trainer.algorithm.name}]x{rounds}")


def assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# armed-S=1 twin trajectories, computed once per (source, dispatch)
_TWINS = {}


def twin(source, dispatch):
    key = (source, dispatch)
    if key not in _TWINS:
        t = build_trainer(make_cfg(source, dispatch, 1))
        _TWINS[key] = run_cell(t, dispatch)
    return _TWINS[key]


# -- the parity matrix -------------------------------------------------------
@pytest.mark.parametrize("source,dispatch", VMAP_CELLS)
@pytest.mark.parametrize("shards", SHARD_COUNTS)
def test_sharded_cell_bitwise_vs_one_shard_twin(source, dispatch,
                                                shards):
    """Every legal sharded vmap cell: bitwise-identical per round to
    its armed 1-shard twin, and its program traces exactly once."""
    if len(jax.devices()) % shards:
        pytest.skip(f"device count does not divide {shards} ways")
    trainer = build_trainer(make_cfg(source, dispatch, shards))
    assert trainer.client_shards == shards
    assert trainer.podscale_armed
    with RecompilationSentinel() as sentinel:
        got = run_cell(trainer, dispatch)
        jax.block_until_ready(jax.tree.leaves(got[0]))
    sentinel.assert_traces(cell_trace_name(trainer, source, dispatch),
                           expected=1)
    if shards == 1:
        _TWINS[(source, dispatch)] = got  # it IS the twin
        return
    assert_trees_equal(got, twin(source, dispatch))


def test_degraded_pod_resume_halves_shards_bitwise():
    """An S=4 checkpoint restored onto S=2 shards continues the armed
    S=1 trajectory bitwise: the hierarchical sum's association depends
    on k alone, so halving the pod replays identical scalar adds."""
    seed, pre, post = 7, 2, 2
    # the uninterrupted reference: armed S=1, pre+post rounds
    t1 = build_trainer(make_cfg("resident", "round", 1))
    ref = run_cell(t1, "round", rounds=pre + post, seed=seed)

    t4 = build_trainer(make_cfg("resident", "round", 4))
    server, clients = t4.init_state(jax.random.key(seed))
    for _ in range(pre):
        server, clients, _ = t4.run_round(server, clients)
    # "checkpoint": pure host bytes, exactly what orbax-style save
    # would serialize — no device placement survives
    ckpt = jax.device_get((server, clients))
    t4.invalidate_stream()

    t2 = build_trainer(make_cfg("resident", "round", 2))
    assert mesh_client_shards(t2.mesh) == 2
    server2 = replicate(ckpt[0], t2.mesh)
    clients2 = shard_clients(ckpt[1], t2.mesh)
    metrics = []
    for _ in range(post):
        server2, clients2, m = t2.run_round(server2, clients2)
        metrics.append(jax.tree.map(np.asarray, m))
    t2.invalidate_stream()
    assert_trees_equal(
        (jax.tree.map(np.asarray, (server2.params, server2.aux)),
         jax.tree.map(np.asarray, clients2), metrics),
        (ref[0], ref[1], ref[2][pre:]))


# -- telemetry gauges (ISSUE 20 satellite: registry-visible) ----------------
def test_podscale_gauges_surface_in_telemetry():
    cfg = make_cfg("feed", "round", 2)
    t = build_trainer(cfg)
    server, clients = t.init_state(jax.random.key(0))
    server, clients, _ = t.run_round(server, clients)
    g = t.telemetry_gauges()
    assert g["client_shards"] == 2.0
    assert g["cohort_allreduce_bytes"] > 0.0
    # single-process runs own every shard, so the producer packs the
    # full cohort — the gauge still reports the sharded-pack path
    assert g["stream_shard_rows"] == float(t.k_dispatch)
    assert g["stream_shard_pack_s"] >= 0.0
    t.invalidate_stream()


def test_hierarchical_sum_is_shard_invariant_standalone():
    """The seam in isolation: S in {1, 2, 4} over the same [k, P]
    payloads produce identical bytes, and the group count is a
    function of k alone."""
    from fedtorch_tpu.parallel.mesh import make_mesh
    k = 8
    assert cohort_group_count(k) == 8
    rng = np.random.RandomState(0)
    payloads = {"w": rng.randn(k, 5).astype(np.float32),
                "n": rng.randint(0, 9, (k,)).astype(np.int32)}
    outs = {}
    for S in SHARD_COUNTS:
        mesh = make_mesh(MeshConfig(client_shards=S))
        arr = jax.device_put(
            jax.tree.map(np.copy, payloads))
        outs[S] = jax.tree.map(np.asarray, jax.jit(
            lambda p: cohort_hierarchical_sum(p, mesh, S))(arr))
    assert_trees_equal(outs[1], outs[2])
    assert_trees_equal(outs[1], outs[4])


# -- named refusals ---------------------------------------------------------
def _reason(cfg, source="resident", dispatch="round",
            execution="vmap", k_online=4, mesh_devices=8):
    alg = make_algorithm(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    return illegal_reason(source, dispatch, execution, cfg=cfg,
                          algorithm=alg, model=model,
                          mesh_devices=mesh_devices, k_online=k_online)


class TestShardedRefusals:
    def test_fused_execution_refused_under_sharding(self):
        reason = _reason(make_cfg("resident", "round", 2),
                         execution="fused")
        assert "until a sharded grouped-conv lowering is measured" \
            in reason

    def test_non_dividing_cohort_refused(self):
        with pytest.raises(ValueError, match="does not divide the "
                                             "dispatch cohort width"):
            build_trainer(make_cfg("resident", "round", 4,
                                   num_clients=12))  # k=6, S=4

    def test_robust_rules_refused(self):
        with pytest.raises(ValueError, match="robust_agg"):
            build_trainer(make_cfg(
                "resident", "round", 2,
                fault_kw={"robust_agg": "median"}))

    def test_cohort_stats_refused(self):
        with pytest.raises(ValueError, match="cohort_stats"):
            build_trainer(make_cfg(
                "resident", "round", 2,
                telemetry_kw={"cohort_stats": True}))

    def test_uncertified_algorithm_refused(self):
        with pytest.raises(ValueError, match="not certified"):
            build_trainer(make_cfg("resident", "round", 2,
                                   algorithm="qffl"))

    def test_shard_gather_mode_refused(self):
        cfg = make_cfg("resident", "round", 2)
        data = build_federated_data(cfg).train
        model = define_model(cfg, batch_size=cfg.data.batch_size)
        with pytest.raises(ValueError,
                           match="not bitwise-stable across shard"):
            FederatedTrainer(cfg, model, make_algorithm(cfg), data,
                             gather_mode="shard")

    def test_auto_gather_never_resolves_shard_when_armed(self):
        # K*B >= n_max would pick 'shard' on a legacy mesh; armed
        # meshes must resolve 'batch' so every shard count traces the
        # same in-program gather plan
        cfg = make_cfg("resident", "round", 2, num_clients=8)
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=cfg.data.batch_size)
        t = FederatedTrainer(cfg, model, make_algorithm(cfg),
                             data.train)
        assert t.gather_mode == "batch"

    def test_non_dividing_commit_buffer_refused(self):
        with pytest.raises(ValueError, match="async commit buffer"):
            build_trainer(make_cfg("resident", "commit", 2,
                                   buffer_size=3))

    def test_non_dividing_device_mesh_refused(self):
        from fedtorch_tpu.parallel.mesh import make_mesh
        with pytest.raises(ValueError, match="does not divide the"):
            make_mesh(MeshConfig(client_shards=3))


# -- the relocated fused-cell multi-device refusal (satellite) --------------
def test_fused_multi_device_refusal_exact_message():
    """The fused execution's one multi-device rule now lives in
    validate_cell (not fusion.py): the EXACT message, raised at
    trainer construction on a multi-device mesh."""
    from fedtorch_tpu.data.batching import stack_partitions
    cfg = ExperimentConfig(
        data=DataConfig(dataset="cifar10", batch_size=6,
                        augment=False, data_plane="device"),
        federated=FederatedConfig(
            federated=True, num_clients=4, online_client_rate=0.5,
            algorithm="fedavg", sync_type="local_step"),
        model=ModelConfig(arch="cnn", conv_impl="conv", norm="bn"),
        optim=OptimConfig(lr=0.05, in_momentum=True),
        train=TrainConfig(local_step=2),
        mesh=MeshConfig(client_fusion="fused"),  # all 8 devices
    ).finalize()
    sizes = (24, 9, 17, 24)
    rng = np.random.RandomState(0)
    feats = rng.randn(sum(sizes), 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, sum(sizes))
    off = np.concatenate([[0], np.cumsum(sizes)])
    parts = [np.arange(off[i], off[i + 1]) for i in range(len(sizes))]
    data = stack_partitions(feats, labels, parts)
    n = len(jax.devices())
    expected = (
        "mesh.client_fusion='fused' is unsupported: mesh has "
        f"{n} devices — the packed client/channel axis must not be "
        "sharded (use the vmap path's client-axis sharding)")
    with pytest.raises(ValueError, match=re.escape(expected)):
        build_trainer(cfg, data)


# -- torn-shard recovery under per-host sharded packing ---------------------
def test_torn_shard_names_owner_and_recovers_bitwise(tmp_path):
    """Under pod-scale per-host packing a torn MmapClientStore shard
    must escalate 'stream.gather' -> 'stream.producer' NAMING the
    owning host and store shard; healing the file and resyncing the
    producer recovers the trajectory bitwise."""
    from fedtorch_tpu.data.streaming import save_client_store
    cfg = make_cfg("feed", "round", 2, store="mmap",
                   store_dir=str(tmp_path))
    data = build_federated_data(cfg)
    save_client_store(str(tmp_path), data.train, clients_per_shard=3)

    # the untouched twin (same sharded config, pristine store)
    twin_t = build_trainer(cfg, data.train)
    ref = run_cell(twin_t, "round", rounds=2, seed=5)

    t = build_trainer(cfg, data.train)
    assert local_cohort_rows(t.mesh, t.k_dispatch,
                             t.client_shards) == (0, t.k_dispatch)
    server, clients = t.init_state(jax.random.key(5))
    torn = {p: p.read_bytes() for p in tmp_path.glob("x.*.bin")}
    for p in torn:
        p.write_bytes(torn[p][:16])  # tear every x shard
    try:
        with pytest.raises(HostSeamError) as ei:
            for _ in range(3):
                server, clients, _ = t.run_round(server, clients)
        assert ei.value.seam == "stream.producer"
        chain, exc = [], ei.value
        while exc is not None:
            chain.append(str(exc))
            exc = exc.__cause__
        blob = " | ".join(chain)
        assert "client-store shard" in blob
        assert "owning host: process 0" in blob
        assert "torn or truncated" in blob

        for p, b in torn.items():  # heal and resync
            p.write_bytes(b)
        t.invalidate_stream()
        metrics = []
        for _ in range(2):
            server, clients, m = t.run_round(server, clients)
            metrics.append(jax.tree.map(np.asarray, m))
        assert_trees_equal(
            (jax.tree.map(np.asarray, (server.params, server.aux)),
             jax.tree.map(np.asarray, clients), metrics),
            ref)
    finally:
        t.invalidate_stream()
