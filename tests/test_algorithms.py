"""Algorithm zoo tests: SCAFFOLD, FedGATE/FedCOMGATE, Qsparse, qFFL.

Each algorithm gets (a) a hand-computed semantic unit test of its
aggregation rule on tiny tensors (SURVEY.md §4 requirement a), and (b) a
convergence smoke test through the full engine."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig, OptimConfig,
    TrainConfig,
)
from fedtorch_tpu.core.state import tree_zeros_like
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer, evaluate


def _cfg(algorithm, **fed_kw):
    return ExperimentConfig(
        federated=FederatedConfig(federated=True, num_clients=4,
                                  algorithm=algorithm, **fed_kw),
        optim=OptimConfig(lr=0.1, lr_scale_at_sync=1.0, weight_decay=0.0),
    ).finalize()


def _trainer(algorithm, lr=0.5, local_step=5, num_clients=8, rate=1.0,
             **fed_kw):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20,
                        batch_size=32, synthetic_alpha=0.5,
                        synthetic_beta=0.5),
        federated=FederatedConfig(federated=True, num_clients=num_clients,
                                  online_client_rate=rate,
                                  algorithm=algorithm,
                                  sync_type="local_step", **fed_kw),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=lr, weight_decay=0.0),
        train=TrainConfig(local_step=local_step),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=32)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)
    return trainer, data


def _run(trainer, rounds, seed=0):
    server, clients = trainer.init_state(jax.random.key(seed))
    for _ in range(rounds):
        server, clients, metrics = trainer.run_round(server, clients)
    return server, clients, metrics


class TestScaffoldSemantics:
    def test_control_variate_update_rule(self):
        """c_i+ = c_i - c + delta/(K*lr); server c += sum(c_i+ - c_i)/N."""
        cfg = _cfg("scaffold")
        alg = make_algorithm(cfg)
        params = {"w": jnp.zeros(2)}
        caux = {"control": {"w": jnp.asarray([0.1, 0.2])}}
        saux = {"control": {"w": jnp.asarray([0.05, 0.05])}}
        delta = {"w": jnp.asarray([1.0, 2.0])}
        K, lr, w = 4, 0.5, 0.25
        payload, new_aux = alg.client_payload(
            delta=delta, client_aux=caux, params=params,
            server_params=params, server_aux=saux, lr=lr, local_steps=K,
            weight=w)
        expected_c_new = np.asarray([0.1, 0.2]) - 0.05 \
            + np.asarray([1.0, 2.0]) / (K * lr)
        np.testing.assert_allclose(np.asarray(new_aux["control"]["w"]),
                                   expected_c_new, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(payload["delta"]["w"]),
                                   np.asarray([0.25, 0.5]), rtol=1e-6)
        # control delta divided by total client count N=4
        np.testing.assert_allclose(
            np.asarray(payload["control_delta"]["w"]),
            (expected_c_new - np.asarray([0.1, 0.2])) / 4, rtol=1e-6)

    def test_grad_correction(self):
        cfg = _cfg("scaffold")
        alg = make_algorithm(cfg)
        g = {"w": jnp.asarray([1.0])}
        out = alg.transform_grads(
            g, params=None, server_params=None,
            client_aux={"control": {"w": jnp.asarray([0.3])}},
            server_aux={"control": {"w": jnp.asarray([0.5])}}, lr=0.1)
        np.testing.assert_allclose(np.asarray(out["w"]), [1.2])

    def test_converges(self):
        trainer, data = _trainer("scaffold")
        server, clients, _ = _run(trainer, 15)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.5


class TestFedGateSemantics:
    def test_grad_tracking_correction(self):
        cfg = _cfg("fedgate")
        alg = make_algorithm(cfg)
        g = {"w": jnp.asarray([1.0])}
        out = alg.transform_grads(
            g, params=None, server_params=None,
            client_aux={"delta": {"w": jnp.asarray([0.4])}},
            server_aux=(), lr=0.1)
        np.testing.assert_allclose(np.asarray(out["w"]), [0.6])

    def test_delta_tracking_update(self):
        cfg = _cfg("fedgate")
        alg = make_algorithm(cfg)
        caux = {"delta": {"w": jnp.asarray([0.0])}}
        new_aux = alg.client_post(
            delta={"w": jnp.asarray([2.0])}, client_aux=caux,
            payload_sum={"w": jnp.asarray([1.5])}, lr=0.5, local_steps=4,
            server_params=None, params=None, weight=0.25)
        # delta_i += (2.0 - 1.5)/(0.5*4) = 0.25
        np.testing.assert_allclose(np.asarray(new_aux["delta"]["w"]),
                                   [0.25])

    def test_compressed_error_feedback(self):
        cfg = _cfg("fedgate", compressed=True, compressed_ratio=1.0)
        alg = make_algorithm(cfg)
        caux = alg.init_client_aux({"w": jnp.zeros(4)})
        assert "memory" in caux
        new_aux = alg.client_post(
            delta={"w": jnp.asarray([1.0, 0.0, 0.0, 0.0])},
            client_aux=caux,
            payload_sum={"w": jnp.asarray([0.5, 0.0, 0.0, 0.0])},
            lr=0.5, local_steps=2, server_params=None, params=None,
            weight=0.5)
        np.testing.assert_allclose(np.asarray(new_aux["memory"]["w"]),
                                   [0.5, 0, 0, 0])

    def test_quantized_downlink_requantizes_once(self):
        """FedCOMGATE: aggregate_transform re-quantizes the aggregated
        sum, and the values land on the quantization grid; server_update
        itself no longer transforms (the engine applies the transform
        once for BOTH server_update and client_post — the reference
        broadcasts the re-quantized tensor, fedgate.py:74-79)."""
        from fedtorch_tpu.ops.quantize import quantize_dequantize
        cfg = _cfg("fedgate", quantized=True, quantized_bits=8)
        alg = make_algorithm(cfg)
        raw = {"w": jnp.linspace(-1.3, 2.7, 64)}
        q = alg.aggregate_transform(raw)
        np.testing.assert_allclose(
            np.asarray(q["w"]),
            np.asarray(quantize_dequantize(raw["w"], 8)), atol=1e-6)
        assert not np.allclose(np.asarray(q["w"]), np.asarray(raw["w"]))

    def test_engine_routes_transformed_sum_to_client_post(self):
        """Monkeypatched aggregate_transform -> zeros must show up in
        BOTH the server step (params unchanged) and the tracking update
        (delta_track == delta_round/(lr*K)), proving the engine hands one
        transformed sum to both consumers."""
        trainer, _ = _trainer("fedgate")
        alg = trainer.algorithm
        alg.aggregate_transform = lambda ps: jax.tree.map(
            jnp.zeros_like, ps)
        server, clients = trainer.init_state(jax.random.key(0))
        p0 = jax.tree.map(lambda x: np.asarray(x), server.params)
        server2, clients2, _ = trainer.run_round(server, clients)
        # zero sum -> server step is a no-op
        for a, b in zip(jax.tree.leaves(p0),
                        jax.tree.leaves(server2.params)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=1e-7)
        # tracking consumed the SAME zero sum: delta_track must be
        # nonzero (= delta_round/(lr*K), not (delta_round - raw_sum))
        track = np.concatenate([
            np.asarray(leaf).ravel()
            for leaf in jax.tree.leaves(clients2.aux["delta"])])
        assert np.abs(track).max() > 0

    @pytest.mark.parametrize("kw", [
        {},
        {"quantized": True, "quantized_bits": 8},     # FedCOMGATE
        {"compressed": True, "compressed_ratio": 1.0},
    ])
    def test_converges(self, kw):
        trainer, data = _trainer("fedgate", **kw)
        server, clients, _ = _run(trainer, 15)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.45, kw


class TestQsparseSemantics:
    def test_sample_size_weights(self):
        cfg = _cfg("qsparse")
        alg = make_algorithm(cfg)

        class FakeData:
            sizes = jnp.asarray([10, 30, 60])
        alg.setup(FakeData)
        w = alg.client_weights((), jnp.asarray([0, 2]), 2.0,
                               jnp.asarray([10, 60]))
        np.testing.assert_allclose(np.asarray(w), [0.1, 0.6])

    def test_converges(self):
        trainer, data = _trainer("qsparse", compressed_ratio=1.0)
        server, clients, _ = _run(trainer, 15)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.45


class TestQFFLSemantics:
    def test_h_normalization_hand_computed(self):
        cfg = _cfg("qffl", qffl_q=1.0)
        alg = make_algorithm(cfg)
        delta = {"w": jnp.asarray([2.0])}
        payload, _ = alg.client_payload(
            delta=delta, client_aux=(), params=None, server_params=None,
            server_aux=(), lr=0.5, local_steps=1, weight=1.0,
            full_loss=jnp.asarray(0.5))
        # scaled = 2 * 0.5^1 / 0.5 = 2 ; h = 1*0.5^0*4 + 0.5/0.5 = 5
        np.testing.assert_allclose(np.asarray(payload["delta"]["w"]), [2.0],
                                   rtol=1e-5)
        assert float(payload["h"]) == pytest.approx(5.0, rel=1e-5)

    def test_q_zero_reduces_to_sum(self):
        """q=0: scaled = delta/lr, h = num_clients/lr -> average*...)"""
        cfg = _cfg("qffl", qffl_q=0.0)
        alg = make_algorithm(cfg)
        payload, _ = alg.client_payload(
            delta={"w": jnp.asarray([1.0])}, client_aux=(), params=None,
            server_params=None, server_aux=(), lr=0.5, local_steps=1,
            weight=1.0, full_loss=jnp.asarray(7.7))
        np.testing.assert_allclose(np.asarray(payload["delta"]["w"]), [2.0])
        assert float(payload["h"]) == pytest.approx(2.0)

    def test_converges(self):
        trainer, data = _trainer("qffl", qffl_q=1.0, lr=0.5)
        server, clients, _ = _run(trainer, 15)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.45


class TestScaffoldBeatsFedAvgOnHeterogeneous:
    def test_variance_reduction_effect(self):
        """SCAFFOLD's control variates should not hurt on skewed data
        (sanity that the correction wiring has the right sign)."""
        t_avg, data = _trainer("fedavg", lr=0.3, local_step=10)
        t_sca, _ = _trainer("scaffold", lr=0.3, local_step=10)
        s_avg, _, _ = _run(t_avg, 12, seed=11)
        s_sca, _, _ = _run(t_sca, 12, seed=11)
        r_avg = evaluate(t_avg.model, s_avg.params, data.test_x,
                         data.test_y, batch_size=128)
        r_sca = evaluate(t_sca.model, s_sca.params, data.test_x,
                         data.test_y, batch_size=128)
        assert float(r_sca.top1) > float(r_avg.top1) - 0.15


def test_scaffold_momentum_caveat_pinned():
    """SCAFFOLD control variates assume plain local SGD: with in_momentum
    the controls over-estimate the mean gradient and training diverges —
    in the reference exactly as here (verified side-by-side on the
    reference's centered scaffold). Pin both behaviors: plain SGD stays
    bounded in a drift regime where momentum blows up."""
    import numpy as np
    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.data.partition import dirichlet_partition
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer

    rng = np.random.RandomState(7)
    C, B, K, N_PER, D = 12, 8, 10, 32, 16
    means = rng.randn(6, D).astype(np.float32) * 1.5
    labels = rng.randint(0, 6, C * N_PER)
    feats = means[labels] + rng.randn(C * N_PER, D).astype(np.float32)
    parts = [p for p in dirichlet_partition(labels, C, concentration=0.3,
                                            seed=1) if len(p)]
    data = stack_partitions(feats, labels, parts)

    def final_loss(momentum: bool) -> float:
        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=D,
                            batch_size=B),
            federated=FederatedConfig(federated=True,
                                      num_clients=data.num_clients,
                                      online_client_rate=1.0,
                                      algorithm="scaffold",
                                      sync_type="local_step"),
            model=ModelConfig(arch="mlp", mlp_num_layers=1,
                              mlp_hidden_size=24),
            optim=OptimConfig(lr=0.1, in_momentum=momentum),
            train=TrainConfig(local_step=K),
            mesh=MeshConfig(num_devices=1),
        ).finalize()
        model = define_model(cfg, batch_size=B)
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
        server, clients = trainer.init_state(jax.random.key(0))
        loss = float("nan")
        for _ in range(12):
            server, clients, m = trainer.run_round(server, clients)
            loss = float(m.train_loss.sum()
                         / max(float(m.online_mask.sum()), 1))
        return loss

    plain = final_loss(False)
    with_mom = final_loss(True)
    assert np.isfinite(plain) and plain < 5.0, plain
    assert not np.isfinite(with_mom) or with_mom > 4 * plain, \
        (plain, with_mom)
