"""Readers validated against FORMAT-FAITHFUL fixtures (VERDICT r3 #7).

tests/test_dataset_readers.py proves the readers parse minimal
structurally-correct files; this module tightens that to fixtures
reproducing the real distributions' quirks (tests/format_fixtures.py
documents each quirk with its public-spec source): TFF writer-id naming
and inverted-background float pixels, multi-snippet Shakespeare clients
with out-of-vocab characters, svmlight sparsity gaps / comments / bz2
compression / MSD regression years.
"""
import numpy as np
import pytest

from fedtorch_tpu.data.datasets import (
    load_emnist, load_libsvm, load_shakespeare, shakespeare_vocab,
)
from format_fixtures import (  # tests/ is on sys.path under pytest
    emnist_writer_id, write_svmlight, write_tff_emnist,
    write_tff_shakespeare,
)


class TestTFFEmnist:
    def test_faithful_file_roundtrip(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        clients = {emnist_writer_id(i): n
                   for i, n in zip(range(4), (7, 3, 5, 2))}
        p = tmp_path / "emnist" / "fed_emnist_digitsonly_train.h5"
        write_tff_emnist(str(p), clients, label_dtype=np.int32)
        # the fixture writes only the train file; the missing test
        # split now raises without the explicit opt-in (ISSUE 3)
        splits = load_emnist(str(tmp_path), full=False,
                             allow_train_as_test=True)
        assert splits.train_x.shape == (17, 28, 28, 1)
        # int32 labels (the real files' dtype) widen to int64
        assert splits.train_y.dtype == np.int64
        # inverted-background convention survives: background is 1.0
        assert float(np.median(splits.train_x)) == 1.0
        # one natural partition per writer, in sorted-id order, sizes
        # matching each writer's example count
        assert len(splits.client_partitions) == 4
        sizes = {cid: n for cid, n in clients.items()}
        for cid, part in zip(sorted(clients), splits.client_partitions):
            assert len(part) == sizes[cid]
        # byte-exact: reading the file back gives the written pixels
        with h5py.File(p, "r") as f:
            first = sorted(clients)[0]
            px = np.asarray(f["examples"][first]["pixels"])
        np.testing.assert_array_equal(
            splits.train_x[splits.client_partitions[0], ..., 0], px)

    def test_full_split_layout(self, tmp_path):
        pytest.importorskip("h5py")
        p = tmp_path / "emnist_full" / "fed_emnist_train.h5"
        write_tff_emnist(str(p), {emnist_writer_id(0): 4})
        splits = load_emnist(str(tmp_path), full=True,
                             allow_train_as_test=True)
        assert splits.train_x.shape == (4, 28, 28, 1)


class TestTFFShakespeare:
    def test_multi_snippet_clients_with_oov(self, tmp_path):
        pytest.importorskip("h5py")
        vocab = shakespeare_vocab()
        # real files: several variable-length snippets per client;
        # include chars outside the 86-char vocabulary (e.g. 'æ', '—')
        clients = {
            "THE_TRAGEDY_OF_HAMLET_HAMLET": [
                "To be, or not to be: that is the question:\n",
                "Whether 'tis nobler in the mind to suffer\n",
                "the slings and arrows of outrageous fortune,",
            ],
            "ALLS_WELL_THAT_ENDS_WELL_HELENA": [
                "Our remedies oft in ourselves do lie — with æther!",
            ],
        }
        p = tmp_path / "shakespeare" / "shakespeare_train.h5"
        write_tff_shakespeare(str(p), clients)
        splits = load_shakespeare(str(tmp_path), seq_len=16)
        assert splits.train_x.shape[1] == 16
        # both clients produced at least one window
        assert len(splits.client_partitions) == 2
        # windows tile the CONCATENATION of a client's snippets: client
        # 1 (sorted first: ALLS_WELL...) has 50 chars -> 3 windows of 16
        text1 = "".join(clients["ALLS_WELL_THAT_ENDS_WELL_HELENA"])
        assert len(splits.client_partitions[0]) == (len(text1) - 1) // 16
        # out-of-vocab characters map to index 0, never crash
        ids = np.asarray(splits.train_x)
        assert ids.max() < len(vocab)
        # next-char shift property holds across snippet joins
        np.testing.assert_array_equal(ids[0, 1:],
                                      np.asarray(splits.train_y)[0, :-1])


class TestSvmlight:
    def test_sparse_gaps_reconstruct_dense(self, tmp_path):
        """Gapped ascending 1-based indices with implicit zeros parse to
        exactly the dense matrix the generator materialized."""
        dense, ys = write_svmlight(
            str(tmp_path / "higgs" / "HIGGS"), 1100, 8, labels="01",
            comments=True)
        splits = load_libsvm("higgs", str(tmp_path))
        got = np.concatenate([splits.train_x, splits.test_x])
        np.testing.assert_allclose(got, dense, rtol=1e-5, atol=1e-8)
        got_y = np.concatenate([splits.train_y, splits.test_y])
        np.testing.assert_array_equal(got_y, (ys > 0).astype(np.int64))

    def test_bz2_compressed_as_distributed(self, tmp_path):
        """rcv1 ships bz2-compressed with {-1,+1} labels; the reader
        must find the .bz2, decompress, and map labels to {0,1}."""
        dense, ys = write_svmlight(
            str(tmp_path / "rcv1" / "rcv1_train.binary.bz2"), 30, 6,
            labels="pm1", compress=True)
        write_svmlight(
            str(tmp_path / "rcv1" / "rcv1_test.binary.bz2"), 10, 6,
            labels="pm1", compress=True, seed=1)
        splits = load_libsvm("rcv1", str(tmp_path))
        assert splits.train_x.shape == (30, 6)
        np.testing.assert_allclose(splits.train_x, dense, rtol=1e-5,
                                   atol=1e-8)
        np.testing.assert_array_equal(
            splits.train_y, (ys > 0).astype(np.int64))

    def test_msd_regression_years_standardized(self, tmp_path):
        """MSD is regression on years: labels stay float years, features
        are standardized train-statistics-only."""
        write_svmlight(str(tmp_path / "MSD" / "YearPredictionMSD"),
                       60, 5, labels="year")
        write_svmlight(str(tmp_path / "MSD" / "YearPredictionMSD.t"),
                       20, 5, labels="year", seed=1)
        splits = load_libsvm("MSD", str(tmp_path))
        assert splits.train_y.dtype == np.float32
        assert splits.train_y.min() >= 1922
        assert splits.train_y.max() <= 2011
        # standardized with train stats: mean ~0, std ~1 on train
        np.testing.assert_allclose(splits.train_x.mean(0),
                                   np.zeros(5), atol=1e-4)
        np.testing.assert_allclose(splits.train_x.std(0),
                                   np.ones(5), atol=1e-2)
