"""COMPARE_REFERENCE.json (scripts/compare_reference.py): the
head-to-head reference claims become machine-checkable (VERDICT item
8) — schema, derived-field consistency, and the accuracy-delta
tolerance band are pinned here, against the payload builder/validator
the script writes through (the full script needs /root/reference
mounted, so the unit surface is what CI can hold)."""
import importlib.util
import json
import os

import pytest

# load WITHOUT executing main(): the module's import surface is
# stdlib-only on purpose (constants + shims + payload helpers)
_spec = importlib.util.spec_from_file_location(
    "compare_reference", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "compare_reference.py"))
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)

build_payload = _mod.build_payload
validate_payload = _mod.validate_payload
COMPARE_SCHEMA = _mod.COMPARE_SCHEMA
ACC_TOLERANCE_PTS = _mod.ACC_TOLERANCE_PTS

GOOD_ROW = {"ref_acc": 78.0, "ours_acc": 77.2, "ref_wall": 120.0,
            "ours_wall": 12.0, "speedup": 10.0}


def payload(**row_overrides):
    return build_payload(
        {"fedavg": dict(GOOD_ROW, **row_overrides)}, rounds=30)


class TestComparePayload:
    def test_good_payload_validates_and_serializes(self, tmp_path):
        p = payload()
        validate_payload(p)
        assert p["schema"] == COMPARE_SCHEMA
        assert p["acc_tolerance_pts"] == ACC_TOLERANCE_PTS
        # the artifact round-trips through JSON unchanged
        path = tmp_path / "COMPARE_REFERENCE.json"
        path.write_text(json.dumps(p))
        validate_payload(json.loads(path.read_text()))

    def test_schema_mismatch_rejected(self):
        p = payload()
        p["schema"] = "fedtorch_tpu.compare_reference/v999"
        with pytest.raises(ValueError, match="schema"):
            validate_payload(p)

    def test_empty_rows_rejected(self):
        with pytest.raises(ValueError, match="no per-algorithm"):
            validate_payload(build_payload({}, rounds=30))

    def test_missing_field_rejected(self):
        p = payload()
        del p["algorithms"]["fedavg"]["speedup"]
        with pytest.raises(ValueError, match="speedup"):
            validate_payload(p)

    def test_non_numeric_field_rejected(self):
        with pytest.raises(ValueError, match="ref_acc"):
            validate_payload(payload(ref_acc="78%"))
        # bool is not an accuracy
        with pytest.raises(ValueError, match="ours_acc"):
            validate_payload(payload(ours_acc=True))

    def test_inconsistent_speedup_rejected(self):
        # speedup must equal ref_wall / ours_wall — a hand-edited
        # artifact cannot overclaim
        with pytest.raises(ValueError, match="speedup"):
            validate_payload(payload(speedup=50.0))

    def test_accuracy_delta_outside_tolerance_rejected(self):
        bad_acc = GOOD_ROW["ref_acc"] - (ACC_TOLERANCE_PTS + 1.0)
        with pytest.raises(ValueError, match="tolerance"):
            validate_payload(payload(ours_acc=bad_acc))

    def test_delta_at_tolerance_boundary_accepted(self):
        validate_payload(
            payload(ours_acc=GOOD_ROW["ref_acc"] - ACC_TOLERANCE_PTS))

    def test_non_positive_wall_rejected(self):
        with pytest.raises(ValueError, match="wall"):
            validate_payload(payload(ours_wall=0.0))

    def test_committed_artifact_validates_if_present(self):
        # when the capture has run (reference box), the committed
        # artifact itself must hold the contract
        path = _mod.OUT_JSON
        if not os.path.exists(path):
            pytest.skip("COMPARE_REFERENCE.json not captured yet "
                        "(needs /root/reference mounted)")
        with open(path) as f:
            validate_payload(json.load(f))
