"""Worker for the watchdog drill (test_watchdog_drill.py): one wedged
process stalls the pod; every process's StallWatchdog must convert the
silent hang into a restartable exit within the timeout.

Two coordinated processes (2 virtual CPU devices each, a 4-device DCN
mesh) run federated rounds with the stall watchdog armed. After round
1, process 1 "dies" (sleeps forever without entering round 2 — the
lost-host failure of docs/multihost.md). Process 0 blocks inside round
2's cross-process collective with NO exception to catch; its watchdog
sees no heartbeat, dumps every thread's stack to stderr, and hard-exits
75. Process 1's watchdog fires the same way (no round completed there
either). The restart harness would then relaunch both on the surviving
slice — the degraded-pod resume path proven by
test_multihost_resume.py.

    python tests/watchdog_worker.py <port> <pid> <timeout_s>
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from mh_common import bringup, configure_env  # noqa: E402

port, pid, timeout_s = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
configure_env(local_devices=2)  # before the first jax import

jax, cfg, trainer = bringup(port, pid, num_processes=2,
                            local_devices=2, online_client_rate=0.5)
from fedtorch_tpu.robustness import StallWatchdog  # noqa: E402

server, clients = trainer.init_state(jax.random.key(0))
watchdog = StallWatchdog(timeout_s).start()

for r in range(6):
    if pid == 1 and r == 2:
        # the "dead host": never enters round 2's collective. Its own
        # watchdog fires too — no round completes here either.
        print(f"WEDGE pid={pid} before round {r}", flush=True)
        time.sleep(3600)
    server, clients, metrics = trainer.run_round(server, clients)
    jax.block_until_ready(server.params)
    watchdog.heartbeat(r)
    print(f"ROUND pid={pid} r={r}", flush=True)

# unreachable when the drill works: the watchdog exits 75 first
watchdog.stop()
print(f"COMPLETED pid={pid}", flush=True)
