"""Epoch-sync semantics under heavy client-size skew (100:1).

The reference's epoch-sync mode stops each client after ITS OWN epoch
budget (``is_sync_fed``, flow_utils.py:33-40): a client with 4 samples
and batch 4 takes exactly 1 step per round while a 400-sample client
takes 100. The engine sizes its lax.scan for the largest client and
early-exits the rest by masking; these tests pin that the masked
trajectory is STEP-FOR-STEP the reference behavior, not a wrap-around
approximation.
"""
import numpy as np
import jax
import jax.numpy as jnp

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, MeshConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.core.losses import make_criterion
from fedtorch_tpu.data.batching import ClientData
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer

DIM, B = 8, 4


def _skewed_data(sizes=(4, 400), seed=0):
    """ClientData with a 100:1 size skew, padded to n_max rows."""
    rng = np.random.RandomState(seed)
    n_max = max(sizes)
    xs, ys = [], []
    for s in sizes:
        x = rng.randn(s, DIM).astype(np.float32)
        y = rng.randint(0, 10, size=s)
        reps = -(-n_max // s)
        xs.append(np.tile(x, (reps, 1))[:n_max])
        ys.append(np.tile(y, reps)[:n_max])
    return ClientData(x=np.stack(xs), y=np.stack(ys).astype(np.int32),
                      sizes=np.asarray(sizes, np.int32))


def _trainer(sizes, rate, algorithm="fedavg", **fed_kw):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=DIM,
                        batch_size=B),
        federated=FederatedConfig(federated=True, num_clients=len(sizes),
                                  online_client_rate=rate,
                                  algorithm=algorithm, sync_type="epoch",
                                  num_epochs_per_comm=1, **fed_kw),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.1, weight_decay=0.0, in_momentum=False),
        train=TrainConfig(),
        mesh=MeshConfig(num_devices=1),
    ).finalize()
    model = define_model(cfg, batch_size=B)
    data = _skewed_data(sizes)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data), data


def test_short_client_takes_exactly_one_reference_step():
    """Round 0 forces client 0 (the 4-sample client) online alone; with
    weight 1 the new server model must equal EXACTLY one SGD step on its
    full 4-sample batch — the reference's early-exit trajectory — even
    though the scan runs 100 lockstep iterations."""
    t, data = _trainer(sizes=(4, 400), rate=0.5)  # k_online = 1
    assert t.local_steps == 100  # scan sized for the large client
    server, clients = t.init_state(jax.random.key(0))
    p0 = jax.tree.map(np.asarray, server.params)

    criterion = make_criterion(False)
    bx = jnp.asarray(data.x[0, :4])
    by = jnp.asarray(data.y[0, :4])

    def loss_fn(p):
        return criterion(t.model.apply(p, bx), by)

    g = jax.grad(loss_fn)(server.params)
    expected = jax.tree.map(lambda p, gg: p - 0.1 * gg, server.params, g)

    server2, clients2, metrics = t.run_round(server, clients)
    assert float(metrics.online_mask[0]) == 1.0
    assert float(metrics.online_mask[1]) == 0.0
    for a, b in zip(jax.tree.leaves(server2.params),
                    jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)
    # not the wrap-around result: 100 wrapped steps would move far more
    moved = sum(float(jnp.abs(a - b).sum()) for a, b in zip(
        jax.tree.leaves(server2.params), jax.tree.leaves(p0)))
    assert moved > 0


def test_per_client_step_budgets_respected():
    """Both clients online: local_index advances by each client's OWN
    budget (1 vs 100) and both end the round at +1.0 epoch."""
    t, _ = _trainer(sizes=(4, 400), rate=1.0)
    server, clients = t.init_state(jax.random.key(1))
    server, clients, _ = t.run_round(server, clients)
    li = np.asarray(clients.local_index)
    ep = np.asarray(clients.epoch)
    assert li[0] == 1 and li[1] == 100, li
    np.testing.assert_allclose(ep, [1.0, 1.0], atol=1e-4)
    # second round: budgets accumulate, never wrap
    server, clients, _ = t.run_round(server, clients)
    li = np.asarray(clients.local_index)
    assert li[0] == 2 and li[1] == 200, li


def test_scaffold_control_uses_effective_steps():
    """SCAFFOLD's control update divides delta by the client's OWN step
    count (scaffold.py:26-27 with K = the client's steps). Round 0
    forces the 4-sample client online alone: its new control must be
    (server0 - x)/(1*lr) = the plain batch gradient, NOT grad/100."""
    t, data = _trainer(sizes=(4, 400), rate=0.5, algorithm="scaffold")
    server, clients = t.init_state(jax.random.key(0))

    criterion = make_criterion(False)
    bx, by = jnp.asarray(data.x[0, :4]), jnp.asarray(data.y[0, :4])
    g = jax.grad(lambda p: criterion(t.model.apply(p, bx), by))(
        server.params)

    server2, clients2, metrics = t.run_round(server, clients)
    assert float(metrics.online_mask[0]) == 1.0
    for got, expect in zip(jax.tree.leaves(clients2.aux["control"]),
                           jax.tree.leaves(g)):
        np.testing.assert_allclose(np.asarray(got)[0], np.asarray(expect),
                                   atol=1e-5, rtol=1e-5)


def test_perfedme_sync_pull_fires_at_own_last_step():
    """PerFedMe pulls w toward theta at the client's last ACTIVE step
    (perfedme.py:115-124 fires where the reference's loop exits). With
    only the short client online, the server model must MOVE — a masked
    pull would make its delta exactly zero."""
    t, _ = _trainer(sizes=(4, 400), rate=0.5, algorithm="perfedme",
                    personal=True)
    server, clients = t.init_state(jax.random.key(0))
    p0 = jax.tree.map(np.asarray, server.params)
    server2, clients2, metrics = t.run_round(server, clients)
    assert float(metrics.online_mask[0]) == 1.0
    moved = sum(float(jnp.abs(a - b).sum()) for a, b in zip(
        jax.tree.leaves(server2.params), jax.tree.leaves(p0)))
    assert moved > 1e-8, "short client's sync pull was masked out"


def test_drfa_snapshot_clamped_into_active_range():
    """DRFA's shared random snapshot step is clamped to each client's
    own budget, so an early-exited client ships a REAL kth model, never
    its zero-initialized placeholder."""
    t, data = _trainer(sizes=(4, 400), rate=0.5, algorithm="fedavg",
                       drfa=True)
    server, clients = t.init_state(jax.random.key(0))

    criterion = make_criterion(False)
    bx, by = jnp.asarray(data.x[0, :4]), jnp.asarray(data.y[0, :4])
    g = jax.grad(lambda p: criterion(t.model.apply(p, bx), by))(
        server.params)
    # short client budget = 1 -> snapshot after its single step:
    # kth = server0 - lr*g; kth_avg = kth / k_online (k_online = 1)
    expected = jax.tree.map(lambda p, gg: p - 0.1 * gg, server.params, g)

    server2, clients2, metrics = t.run_round(server, clients)
    assert float(metrics.online_mask[0]) == 1.0
    for got, expect in zip(jax.tree.leaves(server2.aux["kth_avg"]),
                           jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                                   atol=1e-5, rtol=1e-5)


def test_fedgate_tracking_uses_effective_steps():
    """FedGATE's tracking update divides by the client's OWN steps
    (fedgate.py:102-104): short client's delta_track must reflect 1
    step, not the scan length 100."""
    t, data = _trainer(sizes=(4, 400), rate=0.5, algorithm="fedgate")
    server, clients = t.init_state(jax.random.key(0))

    criterion = make_criterion(False)
    bx, by = jnp.asarray(data.x[0, :4]), jnp.asarray(data.y[0, :4])
    g = jax.grad(lambda p: criterion(t.model.apply(p, bx), by))(
        server.params)

    server2, clients2, metrics = t.run_round(server, clients)
    assert float(metrics.online_mask[0]) == 1.0
    # delta_round = lr*g; payload_sum = w*delta with w=1 (only client);
    # track' = 0 + (delta - payload_sum)/(lr*K_eff) = 0 for this
    # single-client case regardless of K_eff — so instead check via
    # weights 0.5: use both clients online
    t2, data2 = _trainer(sizes=(4, 400), rate=1.0, algorithm="fedgate")
    s, c = t2.init_state(jax.random.key(0))
    s2, c2, _ = t2.run_round(s, c)
    track0 = np.concatenate([np.asarray(leaf)[0].ravel()
                             for leaf in jax.tree.leaves(
                                 c2.aux["delta"])])
    # with effective steps=1 the short client's tracking term
    # (delta - sum)/(lr*1) is ~100x the buggy /(lr*100) version; just
    # pin that it is the same order of magnitude as the raw gradient
    gnorm = float(sum(jnp.abs(x).sum() for x in jax.tree.leaves(g)))
    assert np.abs(track0).sum() > gnorm * 0.05


def test_equal_sizes_unaffected_by_masking():
    """With no skew every step is active — the masked program must match
    the plain local-step program run for the same step count."""
    t_epoch, _ = _trainer(sizes=(40, 40), rate=1.0)
    assert t_epoch.local_steps == 10
    # same engine in local_step mode, same K
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=DIM,
                        batch_size=B),
        federated=FederatedConfig(federated=True, num_clients=2,
                                  online_client_rate=1.0,
                                  algorithm="fedavg",
                                  sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.1, weight_decay=0.0, in_momentum=False),
        train=TrainConfig(local_step=10),
        mesh=MeshConfig(num_devices=1),
    ).finalize()
    model = define_model(cfg, batch_size=B)
    t_steps = FederatedTrainer(cfg, model, make_algorithm(cfg),
                               _skewed_data(sizes=(40, 40)))
    s1, c1 = t_epoch.init_state(jax.random.key(2))
    s2, c2 = t_steps.init_state(jax.random.key(2))
    s1, c1, m1 = t_epoch.run_round(s1, c1)
    s2, c2, m2 = t_steps.run_round(s2, c2)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6)
    np.testing.assert_allclose(np.asarray(m1.train_loss),
                               np.asarray(m2.train_loss), atol=1e-6)
