"""Pipeline-parallel transformer forward (parallel/pipeline.py).

The GPipe schedule must be numerically transparent: staged blocks +
microbatching + ppermute handoffs produce exactly the dense forward."""
import numpy as np
import jax
import pytest
from jax.sharding import Mesh

from fedtorch_tpu.models.transformer import TransformerLM
from fedtorch_tpu.parallel.pipeline import pipeline_apply

# the staged schedule executes inside jax.shard_map; jax releases that
# only expose jax.experimental.shard_map raise AttributeError before
# any pipeline math runs — a version skip, not a red baseline. The
# argument-validation tests raise before shard_map and stay un-marked.
requires_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax does not expose the public jax.shard_map API "
           "(only jax.experimental.shard_map); pipeline_apply needs it")


def _model_and_toks(layers=4, d_model=32, heads=4, seq=24, vocab=48,
                    batch=8):
    model = TransformerLM(vocab_size=vocab, d_model=d_model,
                          num_heads=heads, num_layers=layers, max_len=seq)
    toks = jax.random.randint(jax.random.key(1), (batch, seq), 0, vocab)
    params = model.init(jax.random.key(0), toks)["params"]
    return model, params, toks


@requires_shard_map
@pytest.mark.parametrize("n_pp,microbatches", [(1, 1), (2, 2), (4, 4),
                                               (4, 8), (2, 1)])
def test_pipeline_matches_dense(n_pp, microbatches):
    model, params, toks = _model_and_toks()
    mesh = Mesh(np.asarray(jax.devices()[:n_pp]), ("pp",))
    dense = model.apply({"params": params}, toks)
    out = pipeline_apply(model, params, toks, mesh,
                         num_microbatches=microbatches)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


@requires_shard_map
def test_eight_stage_single_block_each():
    model, params, toks = _model_and_toks(layers=8)
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("pp",))
    dense = model.apply({"params": params}, toks)
    out = pipeline_apply(model, params, toks, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_rejects_indivisible_layers():
    model, params, toks = _model_and_toks(layers=3)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("pp",))
    with pytest.raises(ValueError, match="divisible"):
        pipeline_apply(model, params, toks, mesh)


def test_rejects_indivisible_batch():
    model, params, toks = _model_and_toks(batch=6)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("pp",))
    with pytest.raises(ValueError, match="microbatch"):
        pipeline_apply(model, params, toks, mesh, num_microbatches=4)


@requires_shard_map
def test_pipeline_moe_model():
    """pipeline_apply must thread num_experts into the rebuilt blocks:
    a MoE transformer pipelined over 4 stages equals its dense oracle."""
    model = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                          num_layers=4, max_len=16, num_experts=4)
    toks = jax.random.randint(jax.random.key(1), (4, 16), 0, 32)
    params = model.init(jax.random.key(0), toks)["params"]
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pp",))
    dense = model.apply({"params": params}, toks)
    out = pipeline_apply(model, params, toks, mesh, num_microbatches=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)
