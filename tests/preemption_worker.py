"""Worker for the kill drill (test_kill_drill.py) and the chaos-suite
lifecycle drill: a real `run_experiment` round loop that prints one
bitwise fingerprint per completed round and honors the preemption
drain contract end to end.

The worker is the CLI driver loop verbatim (cli.run_experiment with a
round_callback), so the drill exercises the production code path:
SIGTERM mid-run → flag → SPMD stop poll at the round boundary → final
checkpoint + async drain → exit 75. The restart harness then relaunches
it with ``--resume <ckpt>`` and the remaining rounds' fingerprints must
equal an uninterrupted run's (tests/mh_common.round_fingerprint — repr
precision, so the comparison is bitwise).

    python tests/preemption_worker.py --ckpt DIR --rounds N \
        [--async_checkpoint] [--slow_writes S] [--round_sleep S] \
        [--resume DIR]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

p = argparse.ArgumentParser()
p.add_argument("--ckpt", required=True, help="run directory (--run_dir)")
p.add_argument("--rounds", type=int, default=6)
p.add_argument("--resume", default=None)
p.add_argument("--async_checkpoint", action="store_true")
p.add_argument("--slow_writes", type=float, default=0.0,
               help="inject this many seconds into every checkpoint "
                    "write — puts a write in flight at kill time")
p.add_argument("--round_sleep", type=float, default=0.0,
               help="sleep after each round so the test can land a "
                    "SIGTERM mid-run deterministically")
p.add_argument("--eval_freq", type=int, default=1)
args = p.parse_args()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from mh_common import round_fingerprint  # noqa: E402

from fedtorch_tpu.cli import (  # noqa: E402
    args_to_config, build_parser, run_experiment,
)

if args.slow_writes > 0:
    # slow the WRITE half only (serialization + disk, the part the
    # async worker thread owns) — the snapshot stays on the caller
    from fedtorch_tpu.utils import checkpoint as ckpt_mod
    _orig_write = ckpt_mod._write_checkpoint

    def _slow_write(*a, **kw):
        time.sleep(args.slow_writes)
        return _orig_write(*a, **kw)

    ckpt_mod._write_checkpoint = _slow_write

cli_args = [
    "--federated", "true", "-d", "synthetic", "-a",
    "logistic_regression", "--num_comms", str(args.rounds),
    "--num_workers", "6", "--online_client_rate", "0.5",
    "--federated_sync_type", "local_step", "--local_step", "2",
    "--batch_size", "8", "--lr", "0.1",
    "--eval_freq", str(args.eval_freq),
    "--debug", "false", "--run_dir", args.ckpt,
]
if args.async_checkpoint:
    cli_args.append("--async_checkpoint")
if args.resume:
    cli_args += ["--resume", args.resume]
cfg = args_to_config(build_parser().parse_args(cli_args))


def callback(r, trainer, server, clients, metrics):
    fp = round_fingerprint(jax, trainer, server, clients, metrics)
    print(f"TRAJ round={r} {fp}", flush=True)
    if args.round_sleep > 0:
        time.sleep(args.round_sleep)


res = run_experiment(cfg, round_callback=callback)
if res.get("preempted"):
    print(f"PREEMPTED at_round={res['preempted_at_round']}", flush=True)
    sys.exit(75)
print("DONE", flush=True)
