"""Native C++ host pipeline: build, bindings, and numpy equivalence."""
import numpy as np
import pytest

from fedtorch_tpu.native import (
    HostPrefetcher, cyclic_pad_indices, gather_rows, native_available,
    seeded_permutation,
)


def test_library_builds():
    assert native_available(), "g++ build of pipeline.cpp failed"


def test_seeded_perm_valid_and_deterministic():
    p1 = seeded_permutation(1000, seed=42)
    p2 = seeded_permutation(1000, seed=42)
    p3 = seeded_permutation(1000, seed=43)
    np.testing.assert_array_equal(p1, p2)
    assert not np.array_equal(p1, p3)
    np.testing.assert_array_equal(np.sort(p1), np.arange(1000))


def test_gather_rows_matches_numpy():
    rng = np.random.RandomState(0)
    for dtype in (np.float32, np.int64, np.uint8):
        src = rng.randint(0, 100, (500, 7, 3)).astype(dtype)
        idx = rng.randint(0, 500, 1234)
        np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_gather_rows_multithreaded():
    rng = np.random.RandomState(1)
    src = rng.randn(10000, 32).astype(np.float32)
    idx = rng.randint(0, 10000, 50000)
    np.testing.assert_array_equal(gather_rows(src, idx, num_threads=4),
                                  src[idx])


def test_cyclic_pad():
    idx = np.asarray([3, 1, 4], np.int32)
    out = cyclic_pad_indices(idx, 8)
    np.testing.assert_array_equal(out, [3, 1, 4, 3, 1, 4, 3, 1])


def test_prefetcher_overlaps():
    import time
    produced = []

    def produce(step):
        if step >= 5:
            raise StopIteration
        time.sleep(0.01)
        produced.append(step)
        return step * 2

    pf = HostPrefetcher(produce, depth=2)
    got = []
    while True:
        item = pf.next()
        if item is None:
            break
        got.append(item)
    assert got == [0, 2, 4, 6, 8]
    pf.close()
