"""Native C++ host pipeline: build, bindings, and numpy equivalence."""
import numpy as np
import pytest

from fedtorch_tpu.native import (
    HostPrefetcher, cyclic_pad_indices, gather_rows, native_available,
    seeded_permutation,
)


def test_library_builds():
    assert native_available(), "g++ build of pipeline.cpp failed"


class TestBuildRace:
    """_build_library must never leave a half-written .so where a
    racing process could dlopen it: compile to a temp path, land via
    atomic rename, serialized by a per-path file lock."""

    def _patch_paths(self, tmp_path, monkeypatch):
        import fedtorch_tpu.native.host_pipeline as hp
        src = tmp_path / "src.cpp"
        src.write_text("// fake source")
        monkeypatch.setattr(hp, "_SRC", str(src))
        monkeypatch.setattr(hp, "_LIB_PATH", str(tmp_path / "lib.so"))
        return hp, tmp_path / "lib.so"

    def test_never_compiles_in_place_and_no_tmp_residue(
            self, tmp_path, monkeypatch):
        hp, lib = self._patch_paths(tmp_path, monkeypatch)
        outs = []

        def fake_run(cmd, **kw):
            out = cmd[cmd.index("-o") + 1]
            assert out != str(lib)  # in-place write = the race bug
            outs.append(out)
            with open(out, "wb") as f:
                f.write(b"SO")

        assert hp._build_library(run=fake_run) == str(lib)
        assert lib.read_bytes() == b"SO"
        assert len(outs) == 1
        residue = [p for p in tmp_path.iterdir()
                   if p.name.startswith("lib.so.tmp")]
        assert residue == []

    def test_concurrent_builders_compile_once(self, tmp_path,
                                              monkeypatch):
        import threading
        import time
        hp, lib = self._patch_paths(tmp_path, monkeypatch)
        compiles = []

        def slow_run(cmd, **kw):
            compiles.append(cmd)
            time.sleep(0.2)  # hold the lock long enough to collide
            with open(cmd[cmd.index("-o") + 1], "wb") as f:
                f.write(b"SO")

        results = []
        threads = [threading.Thread(
            target=lambda: results.append(
                hp._build_library(run=slow_run))) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # the loser waited on the lock, re-checked freshness, and
        # adopted the winner's build instead of compiling again
        assert results == [str(lib), str(lib)]
        assert len(compiles) == 1
        assert lib.read_bytes() == b"SO"

    def test_failed_compile_leaves_nothing(self, tmp_path, monkeypatch):
        hp, lib = self._patch_paths(tmp_path, monkeypatch)

        def broken_run(cmd, **kw):
            with open(cmd[cmd.index("-o") + 1], "wb") as f:
                f.write(b"PART")  # partial output before the failure
            raise RuntimeError("compiler died")

        assert hp._build_library(run=broken_run) is None
        assert not lib.exists()
        residue = [p for p in tmp_path.iterdir()
                   if p.name.startswith("lib.so.tmp")]
        assert residue == []


def test_seeded_perm_valid_and_deterministic():
    p1 = seeded_permutation(1000, seed=42)
    p2 = seeded_permutation(1000, seed=42)
    p3 = seeded_permutation(1000, seed=43)
    np.testing.assert_array_equal(p1, p2)
    assert not np.array_equal(p1, p3)
    np.testing.assert_array_equal(np.sort(p1), np.arange(1000))


def test_gather_rows_matches_numpy():
    rng = np.random.RandomState(0)
    for dtype in (np.float32, np.int64, np.uint8):
        src = rng.randint(0, 100, (500, 7, 3)).astype(dtype)
        idx = rng.randint(0, 500, 1234)
        np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_gather_rows_multithreaded():
    rng = np.random.RandomState(1)
    src = rng.randn(10000, 32).astype(np.float32)
    idx = rng.randint(0, 10000, 50000)
    np.testing.assert_array_equal(gather_rows(src, idx, num_threads=4),
                                  src[idx])


def test_cyclic_pad():
    idx = np.asarray([3, 1, 4], np.int32)
    out = cyclic_pad_indices(idx, 8)
    np.testing.assert_array_equal(out, [3, 1, 4, 3, 1, 4, 3, 1])


def test_prefetcher_overlaps():
    import time
    produced = []

    def produce(step):
        if step >= 5:
            raise StopIteration
        time.sleep(0.01)
        produced.append(step)
        return step * 2

    pf = HostPrefetcher(produce, depth=2)
    got = []
    while True:
        item = pf.next()
        if item is None:
            break
        got.append(item)
    assert got == [0, 2, 4, 6, 8]
    pf.close()


class TestSvmlightParser:
    """Native svmlight parser (ft_svmlight_scan/parse) against the
    format-faithful fixture generator AND sklearn's parser."""

    def _gen(self, tmp_path, name, n, f, labels, **kw):
        from format_fixtures import write_svmlight
        path = str(tmp_path / name)
        dense, ys = write_svmlight(path, n, f, labels=labels, **kw)
        return path, dense, ys

    def test_matches_generator_and_sklearn(self, tmp_path):
        import numpy as np
        from fedtorch_tpu.native.host_pipeline import parse_svmlight
        path, dense, ys = self._gen(tmp_path, "train", 200, 12,
                                    "pm1", comments=True)
        with open(path, "rb") as fh:
            got = parse_svmlight(fh.read())
        if got is None:
            import pytest
            pytest.skip("native toolchain unavailable")
        x, y = got
        # the generator's dense matrix holds full doubles; the text
        # carries 6 sig figs, so both parsers see the rounded values
        np.testing.assert_allclose(x, dense.astype(np.float32),
                                   rtol=1e-5, atol=1e-8)
        np.testing.assert_array_equal(y, ys.astype(np.float32))
        # bitwise-identical to sklearn on the same bytes
        from sklearn.datasets import load_svmlight_file
        xs, ys2 = load_svmlight_file(path)
        np.testing.assert_array_equal(
            x, np.asarray(xs.todense(), np.float32))
        np.testing.assert_array_equal(y, ys2.astype(np.float32))

    def test_multithreaded_parse_matches(self, tmp_path):
        import numpy as np
        from fedtorch_tpu.native.host_pipeline import parse_svmlight
        path, dense, ys = self._gen(tmp_path, "big", 5000, 24, "year")
        with open(path, "rb") as fh:
            raw = fh.read()
        got = parse_svmlight(raw, num_threads=4)
        if got is None:
            import pytest
            pytest.skip("native toolchain unavailable")
        x, y = got
        np.testing.assert_allclose(x, dense.astype(np.float32),
                                   rtol=1e-5, atol=1e-8)
        np.testing.assert_array_equal(y, ys.astype(np.float32))

    def test_n_features_override_and_no_trailing_newline(self):
        import numpy as np
        from fedtorch_tpu.native.host_pipeline import parse_svmlight
        got = parse_svmlight(b"1 2:0.5", n_features=6)
        if got is None:
            import pytest
            pytest.skip("native toolchain unavailable")
        x, y = got
        assert x.shape == (1, 6) and y.tolist() == [1.0]
        assert x[0, 1] == np.float32(0.5) and x.sum() == np.float32(0.5)

    def test_malformed_raises(self):
        import pytest
        from fedtorch_tpu.native.host_pipeline import parse_svmlight
        if parse_svmlight(b"1 1:0.5\n") is None:
            pytest.skip("native toolchain unavailable")
        for bad in (b"1 3:0.5 2:0.1\n",   # non-ascending
                    b"1 0:0.5\n",          # index < 1
                    b"1 7:0.5\n",          # > n_features (with override)
                    b"1 2=0.5\n"):         # bad separator
            with pytest.raises(ValueError, match="svmlight"):
                parse_svmlight(bad, n_features=4)

    def test_load_libsvm_uses_native_path(self, tmp_path, monkeypatch):
        """End-to-end through load_libsvm: the engine-facing reader
        produces the same splits whichever parser runs."""
        import numpy as np
        from format_fixtures import write_svmlight
        from fedtorch_tpu.data.datasets import load_libsvm
        base = tmp_path / "rcv1"
        write_svmlight(str(base / "rcv1_train.binary.bz2"), 40, 8,
                       labels="pm1", compress=True)
        write_svmlight(str(base / "rcv1_test.binary.bz2"), 10, 8,
                       labels="pm1", compress=True, seed=1)
        native = load_libsvm("rcv1", str(tmp_path))
        import fedtorch_tpu.native.host_pipeline as hp
        monkeypatch.setattr(hp, "parse_svmlight",
                            lambda *a, **k: None)  # force sklearn
        sk = load_libsvm("rcv1", str(tmp_path))
        np.testing.assert_array_equal(native.train_x, sk.train_x)
        np.testing.assert_array_equal(native.train_y, sk.train_y)
        np.testing.assert_array_equal(native.test_x, sk.test_x)

    def test_missing_value_rejected_not_misparsed(self):
        """A pair with a missing value must raise, not silently consume
        the NEXT line's label as the value (code-review r4)."""
        import pytest
        from fedtorch_tpu.native.host_pipeline import parse_svmlight
        if parse_svmlight(b"1 1:0.5\n") is None:
            pytest.skip("native toolchain unavailable")
        with pytest.raises(ValueError, match="svmlight"):
            parse_svmlight(b"1 2:\n5 1:9\n", n_features=4)

    def test_scan_fast_path_matches_comment_path(self):
        """max_index via the backward last-token walk (no '#') equals
        the tokenizing walk (with '#')."""
        import pytest
        from fedtorch_tpu.native.host_pipeline import parse_svmlight
        plain = b"1 2:0.5 7:1.25\n-1 3:0.1\n"
        commented = b"1 2:0.5 7:1.25 # note\n-1 3:0.1\n"
        a = parse_svmlight(plain)
        if a is None:
            pytest.skip("native toolchain unavailable")
        b = parse_svmlight(commented)
        assert a[0].shape == b[0].shape == (2, 7)
        import numpy as np
        np.testing.assert_array_equal(a[0], b[0])
