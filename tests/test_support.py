"""Support subsystems: logging round-trip through the tools parser,
checkpoint/resume (full round state), meters, and the CLI end-to-end."""
import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig, OptimConfig,
    TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.tools import load_record_file, parse_records, smoothing
from fedtorch_tpu.utils import (
    AverageMeter, PhaseTimer, RunLogger, maybe_resume, save_checkpoint,
)


def _cfg(tmp_path, algorithm="scaffold", num_comms=3):
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=12,
                        batch_size=10),
        federated=FederatedConfig(federated=True, num_clients=4,
                                  num_comms=num_comms,
                                  online_client_rate=1.0,
                                  algorithm=algorithm,
                                  sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.2, weight_decay=0.0),
        train=TrainConfig(local_step=3),
        checkpoint=__import__("fedtorch_tpu.config", fromlist=["x"])
        .CheckpointConfig(checkpoint_dir=str(tmp_path), debug=False),
    ).finalize()


class TestMeters:
    def test_average_meter(self):
        m = AverageMeter()
        for v in (1.0, 2.0, 3.0):
            m.update(v)
        assert m.avg == 2.0 and m.max == 3.0 and m.min == 1.0

    def test_phase_timer(self):
        t = PhaseTimer()
        t.start("round")
        t.stop("round")
        t.new_round()
        t.add_comm(num_bytes=100.0)
        s = t.summary()
        assert "round" in s and s["comm_bytes_total"] == 100.0


class TestLoggingRoundTrip:
    def test_record_parse(self, tmp_path):
        logger = RunLogger(str(tmp_path), debug=False)
        logger.log_train(3, 1.5, 0.42, 0.91, 0.01, comm_bytes=1024,
                         round_time=0.5)
        logger.log_val(3, "test", 0.5, 0.88, 0.99, best=0.9)
        logger.log_comm_time(3, 0.123)
        rec = load_record_file(os.path.join(str(tmp_path), "record0"))
        assert rec["train"][0]["loss"] == pytest.approx(0.42)
        assert rec["train"][0]["comm_bytes"] == 1024
        assert rec["val"][0]["top1"] == pytest.approx(0.88)
        assert rec["val"][0]["mode"] == "test"
        assert rec["comm"][0]["seconds"] == pytest.approx(0.123)

    def test_parse_records_conditions(self, tmp_path):
        run_dir = tmp_path / "lr-0.1_arch-mlp"
        run_dir.mkdir()
        RunLogger(str(run_dir), debug=False).log_train(
            0, 0.0, 1.0, 0.1, 0.1)
        runs = parse_records(str(tmp_path), conditions={"arch": "mlp"})
        assert len(runs) == 1
        assert parse_records(str(tmp_path),
                             conditions={"arch": "resnet"}) == []

    def test_smoothing(self):
        out = smoothing(np.arange(20, dtype=float), window=5)
        assert len(out) == 16
        assert out[0] == pytest.approx(2.0)


class TestPlots:
    def test_styles_deterministic_and_distinct(self):
        from fedtorch_tpu.tools import determine_color_and_lines
        a = determine_color_and_lines(0)
        b = determine_color_and_lines(1)
        assert a == determine_color_and_lines(0)
        assert a != b

    def test_reject_outliers(self):
        from fedtorch_tpu.tools import reject_outliers
        data = np.asarray([1.0, 1.1, 0.9, 1.0, 50.0])
        kept = reject_outliers(data, threshold=1.5)
        assert 50.0 not in kept and len(kept) == 4

    def test_build_legend_from_run_name(self):
        from fedtorch_tpu.tools import build_legend
        name = ("2026-01-01_00-00-00_l2-0.0_lr-0.1_arch-mlp_"
                "alg-fedavg_clients-10")
        assert build_legend(name, ("alg", "clients")) == \
            "alg=fedavg, clients=10"

    def test_plot_runs_writes_figure(self, tmp_path):
        run_dir = tmp_path / "lr-0.1_arch-mlp_alg-fedavg"
        run_dir.mkdir()
        logger = RunLogger(str(run_dir), debug=False)
        for r in range(5):
            logger.log_train(r, float(r), 1.0 / (r + 1), 0.5 + 0.05 * r,
                             0.1)
            logger.log_val(r, "test", 1.0 / (r + 1), 0.5 + 0.05 * r,
                           0.9)
        from fedtorch_tpu.tools import parse_records, plot_runs
        runs = parse_records(str(tmp_path))
        out = tmp_path / "curves.png"
        fig = plot_runs(runs, metric="top1", mode="test",
                        legend_keys=("alg",), out_path=str(out))
        assert out.exists() and out.stat().st_size > 0
        assert fig.axes[0].get_ylabel() == "top1"


class TestCheckpoint:
    def test_full_state_roundtrip(self, tmp_path):
        """SCAFFOLD control variates must survive a resume — the gap the
        reference has (SURVEY.md §5.4)."""
        cfg = _cfg(tmp_path)
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=10)
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                                   data.train)
        server, clients = trainer.init_state(jax.random.key(0))
        for _ in range(2):
            server, clients, _ = trainer.run_round(server, clients)
        save_checkpoint(str(tmp_path / "run"), server, clients, cfg,
                        best_prec1=0.5, is_best=True)

        # fresh states, then restore
        s2, c2 = trainer.init_state(jax.random.key(0))
        s2, c2, best, resumed = maybe_resume(str(tmp_path / "run"), s2, c2,
                                             cfg, None)
        assert resumed and best == 0.5
        assert int(s2.round) == 2
        for a, b in zip(jax.tree.leaves(server.params),
                        jax.tree.leaves(s2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # client control variates restored exactly
        for a, b in zip(jax.tree.leaves(clients.aux["control"]),
                        jax.tree.leaves(c2.aux["control"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # resumed run continues identically to the uninterrupted one
        s_cont, c_cont, _ = trainer.run_round(server, clients)
        s_res, c_res, _ = trainer.run_round(s2, c2)
        for a, b in zip(jax.tree.leaves(s_cont.params),
                        jax.tree.leaves(s_res.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_incompatible_config_rejected(self, tmp_path):
        cfg = _cfg(tmp_path)
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=10)
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                                   data.train)
        server, clients = trainer.init_state(jax.random.key(0))
        save_checkpoint(str(tmp_path / "run"), server, clients, cfg, 0.0,
                        False)
        import dataclasses
        bad = dataclasses.replace(
            cfg, data=dataclasses.replace(cfg.data, batch_size=99))
        with pytest.raises(ValueError, match="batch_size"):
            maybe_resume(str(tmp_path / "run"), server, clients, bad, None)

    def test_missing_checkpoint_raises(self, tmp_path):
        cfg = _cfg(tmp_path)
        with pytest.raises(FileNotFoundError):
            maybe_resume(str(tmp_path / "nope"), None, None, cfg, None)

    def test_async_checkpointer_matches_sync(self, tmp_path):
        """AsyncCheckpointer writes the same bytes as save_checkpoint,
        resumes identically, and leaves no tmp files behind (atomic
        rename)."""
        from fedtorch_tpu.utils import AsyncCheckpointer
        cfg = _cfg(tmp_path)
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=10)
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                                   data.train)
        server, clients = trainer.init_state(jax.random.key(0))
        server, clients, _ = trainer.run_round(server, clients)

        save_checkpoint(str(tmp_path / "sync"), server, clients, cfg,
                        best_prec1=0.4, is_best=True)
        ck = AsyncCheckpointer()
        ck.save(str(tmp_path / "async"), server, clients, cfg,
                best_prec1=0.4, is_best=True)
        ck.close()

        sync_bytes = (tmp_path / "sync" / "checkpoint.ckpt").read_bytes()
        async_bytes = (tmp_path / "async"
                       / "checkpoint.ckpt").read_bytes()
        assert sync_bytes == async_bytes
        assert (tmp_path / "async" / "model_best.ckpt").exists()
        assert not list((tmp_path / "async").glob("*.tmp"))

        s2, c2 = trainer.init_state(jax.random.key(0))
        s2, _, best, resumed = maybe_resume(str(tmp_path / "async"), s2,
                                            c2, cfg, None)
        assert resumed and best == 0.4 and int(s2.round) == 1

    def test_non_writer_process_skips_io(self, tmp_path, monkeypatch):
        """Off process 0 (multi-host), checkpoint saves are no-ops —
        the state is replicated, so N identical writers would race on
        the same files (reference: rank-0-only, eval.py:120-144)."""
        import fedtorch_tpu.utils.checkpoint as ckpt_mod
        monkeypatch.setattr(ckpt_mod.jax, "process_index", lambda: 1)
        cfg = _cfg(tmp_path)
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=10)
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                                   data.train)
        server, clients = trainer.init_state(jax.random.key(0))
        save_checkpoint(str(tmp_path / "p1"), server, clients, cfg,
                        0.0, True)
        from fedtorch_tpu.utils import AsyncCheckpointer
        ck = AsyncCheckpointer()
        ck.save(str(tmp_path / "p1"), server, clients, cfg, 0.0, True)
        ck.close()
        assert not (tmp_path / "p1").exists()

    def test_async_checkpointer_degrades_on_write_errors(self, tmp_path):
        """A failed background write must not vanish — and must not
        poison an unrelated later save() either (the pre-PR-10
        behavior): the checkpointer flips to degraded SYNCHRONOUS
        writes, so a persistent fault raises at the save that actually
        hit it, with the lost write counted (docs/robustness.md 'Host
        plane')."""
        from fedtorch_tpu.robustness import host_recovery
        from fedtorch_tpu.utils import AsyncCheckpointer
        cfg = _cfg(tmp_path)
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=10)
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                                   data.train)
        server, clients = trainer.init_state(jax.random.key(0))
        blocker = tmp_path / "blocked"
        blocker.write_text("a file where a directory must go")
        rec = host_recovery.HostRecovery(sleep_fn=lambda s: None)
        rec.install()
        ck = AsyncCheckpointer()
        try:
            ck.save(str(blocker / "sub"), server, clients, cfg, 0.0,
                    False)
            ck.wait()  # no raise: the loss is recorded, not deferred
            assert ck.degraded and ck.lost_writes == 1
            assert ck.stats()["ckpt_degraded"] == 1.0
            assert "ckpt.write" in rec.degraded
            # degraded mode: the next save runs synchronously and the
            # still-broken target raises HERE, honestly attributed
            with pytest.raises(host_recovery.HostSeamError,
                               match="ckpt.write"):
                ck.save(str(blocker / "sub"), server, clients, cfg,
                        0.0, False)
            # a degraded checkpointer against a HEALTHY target keeps
            # checkpointing (synchronously)
            ck.save(str(tmp_path / "ok"), server, clients, cfg, 0.0,
                    False)
            assert (tmp_path / "ok" / "checkpoint.ckpt").exists()
        finally:
            ck.close()
            rec.uninstall()


class TestCLI:
    def test_end_to_end_federated(self, tmp_path):
        from fedtorch_tpu.cli import main
        results = main([
            "--federated", "true", "--data", "synthetic",
            "--federated_type", "fedavg", "--num_comms", "3",
            "--num_workers", "4", "--online_client_rate", "1.0",
            "--federated_sync_type", "local_step", "--local_step", "3",
            "--arch", "logistic_regression", "--lr", "0.2",
            "--batch_size", "10", "--weight_decay", "0",
            "--checkpoint", str(tmp_path / "ckpt"),
            "--log_dir", str(tmp_path / "logs"), "--debug", "false",
        ])
        assert "best_top1" in results
        # record file written & parseable
        runs = parse_records(str(tmp_path / "ckpt"))
        assert len(runs) == 1
        assert len(runs[0]["records"]["train"]) == 3

    def test_end_to_end_local_sgd(self, tmp_path):
        from fedtorch_tpu.cli import main
        results = main([
            "--federated", "false", "--data", "synthetic",
            "--num_workers", "4", "--num_epochs", "1",
            "--local_step", "2", "--arch", "logistic_regression",
            "--lr", "0.2", "--batch_size", "10",
            "--checkpoint", str(tmp_path / "ckpt"),
            "--debug", "false",
        ])
        assert results["rounds"] > 0

    def test_config_mapping_derivations(self):
        from fedtorch_tpu.cli import args_to_config, build_parser
        args = build_parser().parse_args([
            "--federated", "true", "--federated_type", "afl",
            "--num_comms", "10", "--num_epochs_per_comm", "2",
            "--online_client_rate", "0.5"])
        cfg = args_to_config(args)
        assert cfg.train.num_epochs == 10  # 2*10*0.5
        assert cfg.train.local_step == 1   # afl coercion
