"""The program-level audit (ISSUE 13 tentpole): FTP rules over the
lowered builder cells.

Three layers, mirroring the module's own split:

* **seeded text checks** — each FTP text rule fires on a handcrafted
  StableHLO snippet carrying exactly that violation (and stays quiet
  on the clean twin);
* **seeded lowerings** — real jax programs with an injected violation
  (an f64 cast under x64, a ``jax.debug.print`` host callback, a
  dropped ``donate_argnums``) produce findings through the same
  extraction path the audit uses;
* **the full matrix** — ``audit_programs()`` lowers every legal
  builder cell on the CPU backend and must land ZERO findings with
  the shipped (empty) baseline, refuse the two illegal cells, and
  stay far under the 120 s tier-1 budget.
"""
import json
import time

import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.lint.program_audit import (
    AUDIT_SCAN_LENGTH, LARGE_CONST_BYTES, audit_programs,
    check_collectives, check_donation, check_dtype_promotion,
    check_host_transfers, check_large_constants, check_peak_hbm,
    load_program_baseline, lower_cell, save_program_baseline,
)

CELL = "(resident x round x vmap)"


# -- seeded text checks ------------------------------------------------------

CLEAN_HLO = """\
module @jit_round {
  func.func public @main(%arg0: tensor<8x8xf32> {tf.aliasing_output = 0 : i32}) -> tensor<8x8xf32> {
    %0 = stablehlo.add %arg0, %arg0 : tensor<8x8xf32>
    %1 = stablehlo.custom_call @Sharding(%0) : (tensor<8x8xf32>) -> tensor<8x8xf32>
    return %1 : tensor<8x8xf32>
  }
}
"""


class TestSeededText:
    def test_ftp001_f64(self):
        bad = CLEAN_HLO.replace(
            "stablehlo.add %arg0, %arg0 : tensor<8x8xf32>",
            "stablehlo.convert %arg0 : (tensor<8x8xf32>) -> tensor<8x8xf64>")
        fs = check_dtype_promotion(bad, CELL)
        assert [f.rule for f in fs] == ["FTP001"]
        assert check_dtype_promotion(CLEAN_HLO, CELL) == []

    def test_ftp001_f32_dot_in_bf16_program(self):
        dot = ("    %2 = stablehlo.dot_general %0, %0, contracting_dims "
               "= [1] x [0] : (tensor<8x8xf32>, tensor<8x8xf32>) -> "
               "tensor<8x8xf32>\n")
        bad = CLEAN_HLO.replace("    return", dot + "    return")
        assert [f.rule for f in check_dtype_promotion(
            bad, CELL, compute_dtype="bfloat16")] == ["FTP001"]
        # the same program is fine under the f32 contract
        assert check_dtype_promotion(bad, CELL) == []
        # and a bf16 dot is fine under the bf16 contract
        ok = bad.replace("xf32>", "xbf16>")
        assert check_dtype_promotion(ok, CELL,
                                     compute_dtype="bfloat16") == []

    def test_ftp002_outfeed_and_callback(self):
        bad = CLEAN_HLO.replace(
            "    return",
            '    "stablehlo.outfeed"(%0) : (tensor<8x8xf32>) -> ()\n'
            "    return")
        assert [f.rule for f in check_host_transfers(bad, CELL)] \
            == ["FTP002"]
        bad2 = CLEAN_HLO.replace(
            "custom_call @Sharding",
            "custom_call @xla_python_cpu_callback")
        assert [f.rule for f in check_host_transfers(bad2, CELL)] \
            == ["FTP002"]
        assert check_host_transfers(CLEAN_HLO, CELL) == []

    def test_ftp003_dropped_donation(self):
        bad = CLEAN_HLO.replace(" {tf.aliasing_output = 0 : i32}", "")
        fs = check_donation(bad, CELL, donated_leaves=1)
        assert [f.rule for f in fs] == ["FTP003"]
        assert check_donation(CLEAN_HLO, CELL, donated_leaves=1) == []
        assert check_donation(bad, CELL, donated_leaves=0) == []

    def test_ftp004_collectives_over_budget(self):
        two = CLEAN_HLO.replace(
            "    return",
            '    %c1 = "stablehlo.all_reduce"(%0) : (tensor<8x8xf32>) -> tensor<8x8xf32>\n'
            '    %c2 = "stablehlo.all_reduce"(%0) : (tensor<8x8xf32>) -> tensor<8x8xf32>\n'
            "    return")
        assert [f.rule for f in check_collectives(two, CELL, budget=1)] \
            == ["FTP004"]
        assert check_collectives(two, CELL, budget=2) == []
        assert check_collectives(CLEAN_HLO, CELL, budget=0) == []

    def test_ftp005_large_constant(self):
        small = [("float32[8]", 32)]
        big = [("float32[200,200]", 160_000)]
        assert check_large_constants(small, CELL) == []
        fs = check_large_constants(big, CELL)
        assert [f.rule for f in fs] == ["FTP005"]
        assert big[0][1] > LARGE_CONST_BYTES  # seeded above threshold

    def test_ftp006_peak_regression(self):
        assert check_peak_hbm(1000.0, CELL, {}) == []          # unpinned
        assert check_peak_hbm(None, CELL, {CELL: 500.0}) == []  # no stat
        assert check_peak_hbm(510.0, CELL, {CELL: 500.0}) == []  # in tol
        fs = check_peak_hbm(600.0, CELL, {CELL: 500.0})
        assert [f.rule for f in fs] == ["FTP006"]


# -- seeded real lowerings ---------------------------------------------------

class TestSeededLowerings:
    def test_injected_f64_cast_fires(self):
        from jax.experimental import enable_x64
        with enable_x64():
            low = jax.jit(lambda x: x.astype(jnp.float64) * 2).lower(
                jax.ShapeDtypeStruct((4,), jnp.float32))
            text = low.as_text()
        assert [f.rule for f in check_dtype_promotion(text, CELL)] \
            == ["FTP001"]

    def test_debug_print_fires_ftp002(self):
        def f(x):
            jax.debug.print("x={x}", x=x)
            return x + 1
        text = jax.jit(f).lower(
            jax.ShapeDtypeStruct((4,), jnp.float32)).as_text()
        assert "FTP002" in {f.rule for f in
                            check_host_transfers(text, CELL)}

    def test_dropped_donate_argnums_fires_ftp003(self):
        def f(a, b):
            return a + b, b
        s = jax.ShapeDtypeStruct((8, 8), jnp.float32)
        donated = jax.jit(f, donate_argnums=(0,)).lower(s, s).as_text()
        dropped = jax.jit(f).lower(s, s).as_text()
        assert check_donation(donated, CELL, donated_leaves=1) == []
        assert [f.rule for f in
                check_donation(dropped, CELL, donated_leaves=1)] \
            == ["FTP003"]


# -- the full builder-cell matrix -------------------------------------------

class TestFullMatrix:
    def test_every_cell_lowers_clean_with_empty_baseline(self, tmp_path):
        """The acceptance bar: all legal cells lower and pass with an
        empty FTP baseline, the two fused-commit cells refuse, and the
        whole audit stays far inside the 120 s tier-1 budget."""
        t0 = time.time()
        new, report = audit_programs(log=lambda *_: None)
        wall = time.time() - t0
        assert new == [], [f.render() for f in new]
        legal = {c: r for c, r in report["cells"].items() if r["legal"]}
        refused = {c: r for c, r in report["cells"].items()
                   if not r["legal"]}
        # 10 legal cells + the 6 [shards=2] pod-scale twins of the
        # vmap cells (+ bf16 twins of the vmap round/scan cells)
        assert len([c for c in legal if "[bfloat16]" not in c
                    and "[shards=" not in c]) == 10
        assert len([c for c in legal if "[shards=" in c]) == 6
        assert len([c for c in legal if "[bfloat16]" in c]) == 4
        assert set(refused) == {"(resident x commit x fused)",
                                "(feed x commit x fused)"}
        for cell, rec in refused.items():
            assert cell in rec["refusal"]
        assert wall < 120.0, f"audit took {wall:.1f}s"

    def test_cell_evidence_shape(self):
        ev = lower_cell("feed", "scan", "vmap",
                        scan_length=AUDIT_SCAN_LENGTH)
        assert ev["program"].startswith("rounds_stream_scan")
        assert ev["donated_leaves"] > 0
        assert "stablehlo" in ev["text"] or "func.func" in ev["text"]

    def test_baseline_roundtrip_and_ftp006_gate(self, tmp_path):
        path = str(tmp_path / "program_baseline.json")
        save_program_baseline(path, [], {CELL: 500.0})
        fps, peaks = load_program_baseline(path)
        assert not fps and peaks == {CELL: 500.0}
        doc = json.load(open(path))
        assert doc["version"] == 1
        # a grown watermark now fails through the same check the audit
        # runs per cell
        assert [f.rule for f in check_peak_hbm(600.0, CELL, peaks)] \
            == ["FTP006"]

    def test_shipped_baseline_is_empty(self):
        fps, peaks = load_program_baseline()
        assert sum(fps.values()) == 0
        # peaks may be pinned later by a relay capture; fingerprints
        # must stay empty (findings are fixed, not accepted)


def test_audit_cli_routes():
    """`fedtorch-tpu audit --registry-only` runs jax-free and green."""
    from fedtorch_tpu.cli import main
    assert main(["audit", "--registry-only"]) == 0
