"""Checkpoint lifecycle satellites (ISSUE 4): bounded retention
(``checkpoint.keep_last_n``), the pinned ``run_dir``, and resume edge
cases — a corrupt ``checkpoint.json`` beside a valid per-round keep,
and the heavily-padded template graft (``num_clients`` < device
count, the mesh-shape-independence contract the degraded-pod resume
rides on)."""
import json
import os

import jax
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    CheckpointConfig, DataConfig, ExperimentConfig, FederatedConfig,
    ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.utils import (
    init_checkpoint_dir, maybe_resume, save_checkpoint,
)
from fedtorch_tpu.utils.checkpoint import collect_round_keeps


def make_experiment(num_clients=6, ckpt_kw=None):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=10,
                        batch_size=8),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients, num_comms=4,
            online_client_rate=0.5, algorithm="fedavg",
            sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.1, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        checkpoint=CheckpointConfig(**(ckpt_kw or {})),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                               data.train)
    server, clients = trainer.init_state(jax.random.key(0))
    return cfg, trainer, server, clients


def _round_keeps(d):
    return sorted(f for f in os.listdir(d)
                  if f.startswith("checkpoint_r"))


# -- bounded retention -------------------------------------------------------
class TestKeepLastN:
    def test_gc_keeps_newest_n(self, tmp_path):
        d = str(tmp_path)
        cfg, trainer, server, clients = make_experiment(
            ckpt_kw={"keep_last_n": 2})
        for _ in range(5):
            server, clients, _ = trainer.run_round(server, clients)
            save_checkpoint(d, server, clients, cfg, 0.0, False,
                            save_all=True)
        assert _round_keeps(d) == ["checkpoint_r4.ckpt",
                                   "checkpoint_r5.ckpt"]
        # checkpoint.ckpt itself is never a GC candidate
        assert os.path.exists(os.path.join(d, "checkpoint.ckpt"))

    def test_default_unlimited_preserves_save_all(self, tmp_path):
        d = str(tmp_path)
        cfg, trainer, server, clients = make_experiment()
        assert cfg.checkpoint.keep_last_n == 0
        for _ in range(4):
            server, clients, _ = trainer.run_round(server, clients)
            save_checkpoint(d, server, clients, cfg, 0.0, False,
                            save_all=True)
        assert len(_round_keeps(d)) == 4  # save_all semantics intact

    def test_model_best_never_collected(self, tmp_path):
        d = str(tmp_path)
        cfg, trainer, server, clients = make_experiment(
            ckpt_kw={"keep_last_n": 1})
        for i in range(3):
            server, clients, _ = trainer.run_round(server, clients)
            save_checkpoint(d, server, clients, cfg, 0.5, is_best=True,
                            save_all=True)
        assert _round_keeps(d) == ["checkpoint_r3.ckpt"]
        assert os.path.exists(os.path.join(d, "model_best.ckpt"))
        assert os.path.exists(os.path.join(d, "model_best.json"))

    def test_collect_round_keeps_sorts_numerically(self, tmp_path):
        d = str(tmp_path)
        # r10 must outrank r9 (lexical order would GC it); content is
        # legacy-unframed-shaped — a sub-magic-length stub would count
        # as a torn frame and be swept regardless of retention
        for r in (2, 9, 10):
            with open(os.path.join(d, f"checkpoint_r{r}.ckpt"),
                      "wb") as f:
                f.write(b"legacy-unframed-checkpoint-bytes")
        removed = collect_round_keeps(d, 2)
        assert [os.path.basename(p) for p in removed] == \
            ["checkpoint_r2.ckpt"]
        assert _round_keeps(d) == ["checkpoint_r10.ckpt",
                                   "checkpoint_r9.ckpt"]

    def test_resumed_run_gc_spans_earlier_attempts(self, tmp_path):
        """Retention is directory-wide, not per-process: keeps written
        by the pre-restart attempt are collected by the resumed one."""
        d = str(tmp_path)
        cfg, trainer, server, clients = make_experiment(
            ckpt_kw={"keep_last_n": 2})
        for _ in range(2):  # "first attempt": rounds 1-2
            server, clients, _ = trainer.run_round(server, clients)
            save_checkpoint(d, server, clients, cfg, 0.0, False,
                            save_all=True)
        for _ in range(2):  # "restarted attempt": rounds 3-4
            server, clients, _ = trainer.run_round(server, clients)
            save_checkpoint(d, server, clients, cfg, 0.0, False,
                            save_all=True)
        assert _round_keeps(d) == ["checkpoint_r3.ckpt",
                                   "checkpoint_r4.ckpt"]


# -- run_dir -----------------------------------------------------------------
class TestRunDir:
    def test_run_dir_used_exactly(self, tmp_path):
        d = str(tmp_path / "stable")
        cfg, *_ = make_experiment(ckpt_kw={"run_dir": d})
        assert init_checkpoint_dir(cfg) == d
        assert os.path.isdir(d)

    def test_default_keeps_hyperparam_layout(self, tmp_path):
        cfg, *_ = make_experiment(
            ckpt_kw={"checkpoint_dir": str(tmp_path)})
        path = init_checkpoint_dir(cfg)
        # <root>/<dataset>/<arch>/<hyperparam folder>
        assert path.startswith(
            os.path.join(str(tmp_path), "synthetic",
                         "logistic_regression"))


# -- resume edge cases -------------------------------------------------------
class TestResumeEdgeCases:
    def test_corrupt_meta_beside_valid_keep_skips_cleanly(
            self, tmp_path):
        """checkpoint_index resume reads checkpoint.json for compat:
        undecodable meta beside a perfectly valid per-round .ckpt must
        skip resume with a warning, not die on a JSON traceback."""
        d = str(tmp_path)
        cfg, trainer, server, clients = make_experiment()
        server, clients, _ = trainer.run_round(server, clients)
        save_checkpoint(d, server, clients, cfg, 0.0, False,
                        save_all=True)
        assert os.path.exists(os.path.join(d, "checkpoint_r1.ckpt"))
        with open(os.path.join(d, "checkpoint.json"), "w") as f:
            f.write('{"arguments": {truncated')
        s2, c2 = trainer.init_state(jax.random.key(0))
        with pytest.warns(RuntimeWarning, match="undecodable meta"):
            s3, c3, best, resumed = maybe_resume(d, s2, c2, cfg, "1")
        assert not resumed and best == 0.0
        assert int(jax.device_get(s3.round)) == 0  # fresh state kept

    def test_resume_with_fewer_clients_than_devices(self, tmp_path):
        """num_clients < device count: the 8-device test mesh pads 3
        clients to 8 slots — the checkpoint carries ONLY the 3 real
        clients and the graft must land them in the padded template
        with the trajectory intact (the same contract, at the padding
        extreme, that degraded-pod resume relies on)."""
        d = str(tmp_path)
        C = 3
        cfg, trainer, server, clients = make_experiment(num_clients=C)
        assert trainer.padded_clients >= jax.device_count() > C
        fingerprints = []
        for _ in range(4):
            server, clients, m = trainer.run_round(server, clients)
            jax.block_until_ready(server.params)
            fingerprints.append(repr(float(m.train_loss.sum())))
        # checkpoint at round 2 of a REPLAY from the same seed
        cfg2, tr2, s2, c2 = make_experiment(num_clients=C)
        for _ in range(2):
            s2, c2, _ = tr2.run_round(s2, c2)
        save_checkpoint(d, s2, c2, cfg2, 0.0, False)
        # fresh trainer resumes and must reproduce rounds 3-4 bitwise
        cfg3, tr3, s3, c3 = make_experiment(num_clients=C)
        s3, c3, _, resumed = maybe_resume(d, s3, c3, cfg3, None)
        assert resumed and int(jax.device_get(s3.round)) == 2
        tail = []
        for _ in range(2):
            s3, c3, m = tr3.run_round(s3, c3)
            jax.block_until_ready(s3.params)
            tail.append(repr(float(m.train_loss.sum())))
        assert tail == fingerprints[2:]
