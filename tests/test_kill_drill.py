"""Kill drill (ISSUE 4 acceptance): SIGTERM mid-run → drained
checkpoint + exit 75 → run_elastic relaunches with --resume → the
stitched trajectory is bitwise identical to an uninterrupted run.

The worker (tests/preemption_worker.py) is the production CLI round
loop (cli.run_experiment) with a fingerprint callback; the harness is
the real ElasticRunner with an injected popen that lands a SIGTERM on
the first child after its second completed round. Two variants: sync
checkpointing, and --async_checkpoint with writes slowed so one is in
flight at kill time (the drain must still land every queued write
before exiting).
"""
import os
import re
import signal
import subprocess
import sys
import threading

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from fedtorch_tpu.robustness.harness import ElasticRunner  # noqa: E402

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "preemption_worker.py")
_TRAJ = re.compile(r"^(TRAJ round=\d+ .*)$", re.M)
ROUNDS = 6


def _worker_env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU relay in workers
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(
        __file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root, env.get("PYTHONPATH", "")])
    return env


def _baseline(ckpt_dir: str):
    """Uninterrupted run — the reference trajectory."""
    out = subprocess.run(
        [sys.executable, _WORKER, "--ckpt", ckpt_dir,
         "--rounds", str(ROUNDS)],
        capture_output=True, text=True, timeout=300, env=_worker_env())
    assert out.returncode == 0, out.stdout + out.stderr
    traj = _TRAJ.findall(out.stdout)
    assert len(traj) == ROUNDS, out.stdout
    return traj


def _drill(ckpt_dir: str, extra_args):
    """Run the worker under ElasticRunner; SIGTERM the FIRST child
    after its second TRAJ line; return (rc, per-child lines, harness
    log)."""
    cmd = [sys.executable, _WORKER, "--ckpt", ckpt_dir,
           "--rounds", str(ROUNDS), "--round_sleep", "0.5"] + extra_args
    outs, logs, readers = [], [], []
    env = _worker_env()

    def popen(c, **kw):
        proc = subprocess.Popen(c, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                bufsize=1, env=env)
        lines = []
        outs.append(lines)
        kill_this = len(outs) == 1

        def reader():
            for line in proc.stdout:
                lines.append(line.rstrip("\n"))
                if kill_this and sum(
                        1 for ln in lines
                        if ln.startswith("TRAJ")) == 2:
                    try:
                        os.kill(proc.pid, signal.SIGTERM)
                    except ProcessLookupError:  # raced to exit
                        pass

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        readers.append(t)
        return proc

    runner = ElasticRunner(cmd, ckpt_dir=ckpt_dir, max_restarts=3,
                           popen=popen, sleep_fn=lambda s: None,
                           log_fn=logs.append)
    rc = runner.run()
    for t in readers:
        t.join(timeout=30)
    return rc, runner, outs, logs


def _check_drill(baseline, rc, runner, outs, logs):
    assert rc == 0, (outs, logs)
    # exactly one restart: kill -> 75 -> relaunch -> completion
    assert runner.launches == 2, logs
    assert any("exited 75 (restartable)" in ln for ln in logs), logs
    # the first child really drained (not just died)
    assert any(ln.startswith("PREEMPTED") for ln in outs[0]), outs[0]
    # the relaunch carried --resume (a checkpoint existed)
    assert any("--resume" in ln and "launch #2" in ln
               for ln in logs), logs
    stitched = [ln for lines in outs for ln in lines
                if ln.startswith("TRAJ")]
    # no round lost, none repeated, every fingerprint bitwise equal
    assert stitched == baseline, (baseline, stitched)


@pytest.mark.slow
def test_kill_drill_sync_checkpoint(tmp_path):
    baseline = _baseline(str(tmp_path / "base"))
    rc, runner, outs, logs = _drill(str(tmp_path / "drill"), [])
    _check_drill(baseline, rc, runner, outs, logs)


@pytest.mark.slow
def test_kill_drill_async_write_in_flight(tmp_path):
    """--async_checkpoint with every write slowed 0.4s: the kill lands
    with a queued/in-flight write; the drain must flush it AND the
    final checkpoint before exiting 75."""
    baseline = _baseline(str(tmp_path / "base"))
    rc, runner, outs, logs = _drill(
        str(tmp_path / "drill"),
        ["--async_checkpoint", "--slow_writes", "0.4"])
    _check_drill(baseline, rc, runner, outs, logs)
