"""Shared bring-up for the multi-host worker scripts and tests.

Single source of the multihost contract (env ordering before the first
jax import, sitecustomize scrub, shared-seed config/data build) so the
2-process smoke (multihost_worker.py) and the 4-process
interrupt-resume scenario (multihost_resume_worker.py) cannot drift.
"""
from __future__ import annotations

import os
import socket


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_workers(script: str, extra_args, n_procs: int, *,
                timeout: int = 420, expect_rc: int = 0):
    """Launch ``n_procs`` coordinated worker processes of ``script``
    (argv: port, pid, *extra_args) and return their merged outputs.

    Single source of the fan-out plumbing: fresh port, TPU-proxy env
    scrub, repo-root PYTHONPATH, communicate-with-timeout + kill-all,
    per-pid returncode assertion (``expect_rc``; the watchdog drill
    expects the restartable code 75 instead of 0). Used by
    test_multihost.py, test_multihost_resume.py and
    test_watchdog_drill.py."""
    import subprocess
    import sys

    import pytest

    port = free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU relay in workers
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(
        __file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root, env.get("PYTHONPATH", "")])
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(port), str(pid)]
            + [str(a) for a in extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        for pid in range(n_procs)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"{os.path.basename(script)}: worker timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == expect_rc, (
            f"worker {pid} exited {p.returncode} "
            f"(expected {expect_rc}):\n{out}")
    return outs


def configure_env(local_devices: int) -> None:
    """MUST run before the first ``import jax`` in the process."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                               f"{local_devices}")
    # keep the TPU-proxy sitecustomize (if present) off the workers
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def bringup(port: str, pid: int, *, num_processes: int,
            local_devices: int, online_client_rate: float):
    """Distributed init + the shared seeded experiment; returns
    (jax, cfg, trainer). Every process derives identical
    data/partitions from the shared seed — the determinism contract
    that replaces the reference's rank-0 broadcast (partition.py:25-33;
    docs/multihost.md 'Determinism across hosts')."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data import build_federated_data
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer, init_multihost

    mesh_cfg = MeshConfig(coordinator_address=f"localhost:{port}",
                          num_processes=num_processes, process_id=pid)
    init_multihost(mesh_cfg)
    assert jax.process_count() == num_processes, jax.process_count()
    assert len(jax.local_devices()) == local_devices

    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=12,
                        batch_size=8),
        federated=FederatedConfig(federated=True, num_clients=10,
                                  online_client_rate=online_client_rate,
                                  algorithm="fedavg",
                                  sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.1, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        mesh=mesh_cfg,
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg),
                               data.train)
    assert trainer.mesh.devices.size == num_processes * local_devices
    return jax, cfg, trainer


def round_fingerprint(jax, trainer, server, clients, metrics) -> str:
    """Full-precision per-round fingerprint (loss sum, mean epoch,
    squared param norm) — repr so comparisons are bitwise."""
    loss = float(metrics.train_loss.sum())
    epoch = trainer.mean_client_epoch(clients)
    pnorm = float(sum(jax.numpy.sum(x * x)
                      for x in jax.tree.leaves(server.params)))
    return f"loss={loss!r} epoch={epoch!r} pnorm={pnorm!r}"
