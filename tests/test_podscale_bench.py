"""Slow-lane smoke for the pod-scale shard-sweep bench
(scripts/podscale_bench.py → PODSCALE_AB.json): the capture must run
end to end on the forced 8-device CPU mesh, report bitwise parity
against the 1-shard twin at every shard count, zero timed-window
retraces, and a compare-able run dir — so the on-chip capture
(tpu_capture.sh `podscale` step) cannot be the first time the script
ever executes."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_podscale_bench_smoke(tmp_path):
    out_path = str(tmp_path / "PODSCALE_AB.json")
    runs_dir = str(tmp_path / "podscale_northstar")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PODSCALE_BENCH_SMOKE="1", PODSCALE_AB_PATH=out_path,
               PODSCALE_RUNS_DIR=runs_dir)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "podscale_bench.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(out_path) as f:
        report = json.load(f)
    # the forced 8-device mesh admits the whole smoke sweep
    assert report["config"]["shard_sweep"] == [1, 2, 4]
    assert set(report["shards"]) == {"1", "2", "4"}
    for s, arm in report["shards"].items():
        # the hard bars, per arm: bitwise vs the 1-shard twin and
        # trace-once (the timed window is retrace-free)
        assert arm["parity_bitwise_vs_one_shard"] is True, s
        assert arm["retraces_during_timed_rounds"] == 0, s
        assert arm["ms_per_round"] > 0
        assert arm["clients_per_s"] == pytest.approx(
            arm["k_dispatch"] * arm["rounds_per_s"])
    # sharded arms moved the seam's one all-reduce
    assert report["shards"]["2"]["cohort_allreduce_bytes"] > 0
    assert report["ok"] is True
    # the compare-able artifact: metrics/v1 header + per-round rows
    # carrying the pod-scale gauges the scaling gate reads
    with open(os.path.join(runs_dir, "metrics.jsonl")) as f:
        lines = [json.loads(ln) for ln in f]
    assert lines[0]["schema"] == "fedtorch_tpu.metrics/v1"
    assert lines[0]["run"]["client_shards"] == 4
    for row in lines[1:]:
        assert row["client_shards"] == 4.0
        assert row["cohort_allreduce_bytes"] > 0
