"""Federated engine tests: hand-computed aggregation, convergence smoke
tests (SURVEY.md §4 requirements a & d), determinism, and participation
semantics."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig, OptimConfig,
    TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer, evaluate
from fedtorch_tpu.parallel.federated import participation_indices


def make_trainer(algorithm="fedavg", num_clients=8, rate=1.0, lr=0.1,
                 local_step=5, dataset="synthetic", arch="logistic_regression",
                 mesh_kw=None, **fed_kw):
    from fedtorch_tpu.config import MeshConfig
    cfg = ExperimentConfig(
        data=DataConfig(dataset=dataset, synthetic_dim=20, batch_size=32,
                        synthetic_alpha=0.5, synthetic_beta=0.5),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients, num_comms=20,
            online_client_rate=rate, algorithm=algorithm,
            sync_type="local_step", **fed_kw),
        model=ModelConfig(arch=arch),
        optim=OptimConfig(lr=lr, weight_decay=0.0),
        train=TrainConfig(local_step=local_step),
        mesh=MeshConfig(**(mesh_kw or {})),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    alg = make_algorithm(cfg)
    return FederatedTrainer(cfg, model, alg, data.train), data, cfg


class TestParticipation:
    def test_round0_forces_client0(self):
        for seed in range(5):
            idx = participation_indices(jax.random.key(seed), 10, 3,
                                        jnp.asarray(0))
            assert 0 in np.asarray(idx)

    def test_later_rounds_uniform(self):
        seen = set()
        for seed in range(20):
            idx = participation_indices(jax.random.key(seed), 10, 3,
                                        jnp.asarray(5))
            arr = np.asarray(idx)
            assert len(np.unique(arr)) == 3
            seen.update(arr.tolist())
        assert len(seen) == 10  # every client eventually sampled


class TestFedAvgAggregation:
    def test_one_round_hand_computed(self):
        """Full participation, 1 local step, lr known -> the server update
        equals the average client delta (fedavg.py semantics)."""
        trainer, data, cfg = make_trainer(num_clients=4, rate=1.0,
                                          local_step=1, lr=0.1)
        server, clients = trainer.init_state(jax.random.key(0))
        s0 = jax.tree.map(np.asarray, server.params)

        server2, clients2, metrics = trainer.run_round(server, clients)

        # reconstruct: every client does one SGD step from s0 on its own
        # batch; delta_i = s0 - x_i = lr * g_i; server p = s0 - mean(delta)
        new_clients_params = jax.tree.map(np.asarray, clients2.params)
        # all clients end the round holding the server model
        for leaf in jax.tree.leaves(new_clients_params):
            for c in range(1, 4):
                np.testing.assert_allclose(leaf[c], leaf[0], atol=1e-6)
        s2 = jax.tree.map(np.asarray, server2.params)
        # server changed
        assert any(np.abs(a - b).max() > 0
                   for a, b in zip(jax.tree.leaves(s0),
                                   jax.tree.leaves(s2)))

    def test_weights_sum_to_one_with_client0(self):
        """Regression test: weights must sum to 1 when client 0 is online
        (reference rank_weight rule, fedavg.py:18-27) — a double
        normalization once silently halved every server update."""
        cfg = ExperimentConfig(federated=FederatedConfig(
            federated=True, algorithm="fedavg")).finalize()
        alg = make_algorithm(cfg)
        idx = jnp.asarray([0, 3, 5, 7])
        w = alg.client_weights((), idx, 4.0, jnp.ones(4))
        assert float(jnp.sum(w)) == pytest.approx(1.0)
        # client 0 offline: denominator is k+1 (rank-0 server quirk)
        w2 = alg.client_weights((), jnp.asarray([2, 3, 5, 7]), 5.0,
                                jnp.ones(4))
        assert float(jnp.sum(w2)) == pytest.approx(4.0 / 5.0)

    def test_weighted_sum_matches_manual(self):
        """Drive the algorithm object directly with synthetic deltas."""
        cfg = ExperimentConfig(federated=FederatedConfig(
            federated=True, algorithm="fedavg")).finalize()
        alg = make_algorithm(cfg)
        delta = {"w": jnp.asarray([1.0, 2.0])}
        payload, _ = alg.client_payload(
            delta=delta, client_aux=(), params=None, server_params=None,
            server_aux=(), lr=0.1, local_steps=5,
            weight=jnp.asarray(0.25))
        np.testing.assert_allclose(np.asarray(payload["w"]), [0.25, 0.5])


class TestConvergence:
    def test_fedavg_logistic_converges(self):
        trainer, data, cfg = make_trainer(num_clients=8, rate=1.0,
                                          local_step=5, lr=0.5)
        server, clients = trainer.init_state(jax.random.key(1))
        first_loss = None
        for r in range(15):
            server, clients, metrics = trainer.run_round(server, clients)
            loss = float(jnp.sum(metrics.train_loss)
                         / jnp.maximum(jnp.sum(metrics.online_mask), 1))
            if first_loss is None:
                first_loss = loss
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert loss < first_loss * 0.8, (first_loss, loss)
        assert float(res.top1) > 0.5

    def test_partial_participation_converges(self):
        trainer, data, cfg = make_trainer(num_clients=8, rate=0.5,
                                          local_step=5, lr=0.5)
        server, clients = trainer.init_state(jax.random.key(2))
        for r in range(20):
            server, clients, metrics = trainer.run_round(server, clients)
            assert float(jnp.sum(metrics.online_mask)) == 4.0
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.5

    def test_fedprox_converges(self):
        trainer, data, cfg = make_trainer(algorithm="fedprox",
                                          num_clients=8, rate=1.0,
                                          local_step=5, lr=0.5)
        server, clients = trainer.init_state(jax.random.key(3))
        for r in range(15):
            server, clients, _ = trainer.run_round(server, clients)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.5

    def test_fedadam_converges(self):
        trainer, data, cfg = make_trainer(algorithm="fedadam",
                                          num_clients=8, rate=1.0,
                                          local_step=5, lr=0.5,
                                          fedadam_tau=0.1)
        server, clients = trainer.init_state(jax.random.key(4))
        for r in range(15):
            server, clients, _ = trainer.run_round(server, clients)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.5

    def test_quantized_fedavg_converges(self):
        trainer, data, cfg = make_trainer(num_clients=8, rate=1.0,
                                          local_step=5, lr=0.5,
                                          quantized=True, quantized_bits=8)
        server, clients = trainer.init_state(jax.random.key(5))
        for r in range(15):
            server, clients, _ = trainer.run_round(server, clients)
        res = evaluate(trainer.model, server.params, data.test_x,
                       data.test_y, batch_size=128)
        assert float(res.top1) > 0.5


class TestDeterminism:
    def test_same_seed_same_result(self):
        t1, _, _ = make_trainer(num_clients=4, rate=0.5)
        s1, c1 = t1.init_state(jax.random.key(7))
        s2, c2 = t1.init_state(jax.random.key(7))
        s1, c1, _ = t1.run_round(s1, c1)
        s2, c2, _ = t1.run_round(s2, c2)
        for a, b in zip(jax.tree.leaves(s1.params),
                        jax.tree.leaves(s2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestBatchedRounds:
    def test_run_rounds_matches_run_round(self):
        """run_rounds (N rounds in one lax.scan device call, the bench
        fast path) must reproduce N sequential run_round calls exactly:
        same server params, same client state, same per-round metrics."""
        trainer, _, _ = make_trainer(rate=0.5, local_step=3)
        s1, c1 = trainer.init_state(jax.random.key(0))
        s2, c2 = trainer.init_state(jax.random.key(0))
        seq_metrics = []
        for _ in range(3):
            s1, c1, m = trainer.run_round(s1, c1)
            seq_metrics.append(m)
        s2, c2, ms = trainer.run_rounds(s2, c2, 3)
        for a, b in zip(jax.tree.leaves(s1.params),
                        jax.tree.leaves(s2.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)
        for a, b in zip(jax.tree.leaves(c1), jax.tree.leaves(c2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)
        for r in range(3):
            np.testing.assert_allclose(
                np.asarray(ms.train_loss[r]),
                np.asarray(seq_metrics[r].train_loss), atol=1e-6)
            np.testing.assert_array_equal(
                np.asarray(ms.online_mask[r]),
                np.asarray(seq_metrics[r].online_mask))

    def test_run_rounds_on_sharded_mesh(self):
        """The scanned driver composes with the sharded client axis."""
        trainer, _, _ = make_trainer(mesh_kw={"num_devices": 8})
        s, c = trainer.init_state(jax.random.key(1))
        s, c, ms = trainer.run_rounds(s, c, 2)
        loss = np.asarray(ms.train_loss.sum(-1) / ms.online_mask.sum(-1))
        assert loss.shape == (2,) and np.all(np.isfinite(loss))


class TestScanUnroll:
    def test_unrolled_scan_matches_default(self):
        """mesh.scan_unroll is a compile-time pipelining knob; the local
        steps are data-dependent so unrolling must not change results."""
        t1, _, _ = make_trainer(num_clients=4, rate=0.5, local_step=5)
        t2, _, _ = make_trainer(num_clients=4, rate=0.5, local_step=5,
                                mesh_kw={"scan_unroll": 5})
        s1, c1 = t1.init_state(jax.random.key(3))
        s2, c2 = t2.init_state(jax.random.key(3))
        for _ in range(2):
            s1, c1, _ = t1.run_round(s1, c1)
            s2, c2, _ = t2.run_round(s2, c2)
        for a, b in zip(jax.tree.leaves(s1.params),
                        jax.tree.leaves(s2.params)):
            # unrolling preserves the data-dependent step order, but XLA
            # may fuse the unrolled body differently, so allow ulp-level
            # slack rather than demanding bitwise identity
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)


class TestMLPEngine:
    def test_mlp_round_runs(self):
        trainer, data, cfg = make_trainer(arch="mlp", num_clients=4,
                                          rate=1.0, local_step=2, lr=0.1)
        server, clients = trainer.init_state(jax.random.key(0))
        server, clients, metrics = trainer.run_round(server, clients)
        assert np.isfinite(float(jnp.sum(metrics.train_loss)))


class TestAsyncGateMatrix:
    """ISSUE 6 satellite: every unsupported combination of
    `--sync_mode async` must raise ONE clear ValueError naming the
    gate at construction (the stream-plane gate style) — never fail
    deep in tracing."""

    def _async_cfg(self, algorithm="fedavg", num_clients=12, rate=0.5,
                   mesh_kw=None, **fed_kw):
        from fedtorch_tpu.config import MeshConfig
        return ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=20,
                            batch_size=32, synthetic_alpha=0.5,
                            synthetic_beta=0.5),
            federated=FederatedConfig(
                federated=True, num_clients=num_clients, num_comms=4,
                online_client_rate=rate, algorithm=algorithm,
                sync_type="local_step", sync_mode="async", **fed_kw),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.1, weight_decay=0.0),
            train=TrainConfig(local_step=2),
            mesh=MeshConfig(**(mesh_kw or {})),
        ).finalize()

    def _build(self, cfg, **kw):
        from fedtorch_tpu.async_plane import AsyncFederatedTrainer
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=cfg.data.batch_size)
        return AsyncFederatedTrainer(cfg, model, make_algorithm(cfg),
                                     data.train, **kw)

    @pytest.mark.parametrize("algorithm", [
        "fedgate", "afl", "qffl", "qsparse", "apfl", "perfedme",
        "perfedavg"])
    def test_gated_algorithms_raise_named_gate(self, algorithm):
        cfg = self._async_cfg(algorithm=algorithm)
        with pytest.raises(ValueError,
                           match="sync_mode='async' is unsupported"):
            self._build(cfg)

    def test_drfa_wrapper_gated(self):
        cfg = self._async_cfg(algorithm="fedavg", drfa=True)
        with pytest.raises(ValueError, match="drfa"):
            self._build(cfg)

    @pytest.mark.parametrize("algorithm", [
        "fedavg", "fedprox", "fedadam", "scaffold"])
    def test_supported_algorithms_construct(self, algorithm):
        cfg = self._async_cfg(algorithm=algorithm)
        self._build(cfg)  # must not raise

    def test_fused_client_fusion_gated(self):
        cfg = self._async_cfg(mesh_kw={"client_fusion": "fused"})
        with pytest.raises(ValueError, match="client_fusion"):
            self._build(cfg)

    def test_shard_gather_gated(self):
        cfg = self._async_cfg()
        with pytest.raises(ValueError, match="shard"):
            self._build(cfg, gather_mode="shard")

    def test_buffer_exceeding_concurrency_gated(self):
        cfg = self._async_cfg(async_buffer_size=5, async_concurrency=2)
        with pytest.raises(ValueError, match="async_buffer_size"):
            self._build(cfg)

    def test_too_small_population_gated(self):
        cfg = self._async_cfg(num_clients=6, rate=1.0)
        with pytest.raises(ValueError, match="num_clients"):
            self._build(cfg)

    def test_base_trainer_refuses_async_config(self):
        cfg = self._async_cfg()
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=cfg.data.batch_size)
        with pytest.raises(ValueError, match="AsyncFederatedTrainer"):
            FederatedTrainer(cfg, model, make_algorithm(cfg),
                             data.train)

    def test_run_rounds_refused_on_async_plane(self):
        trainer = self._build(self._async_cfg())
        server, clients = trainer.init_state(jax.random.key(0))
        with pytest.raises(ValueError, match="run_rounds"):
            trainer.run_rounds(server, clients, 2)
