"""MoE layer + expert parallelism (models/transformer.MoEMLP,
parallel/expert.py)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from fedtorch_tpu.models.transformer import MoEMLP, TransformerLM
from fedtorch_tpu.parallel.expert import ep_moe_apply


def _layer(E=8, d=16, B=2, T=12):
    layer = MoEMLP(num_experts=E)
    x = jax.random.normal(jax.random.key(1), (B, T, d))
    params = layer.init(jax.random.key(0), x)["params"]
    return layer, params, x


class TestMoELayer:
    def test_tokens_route_to_argmax_expert(self):
        """Each token's output must equal its top-1 expert's MLP output
        scaled by the gate probability (capacity = all tokens, exact)."""
        layer, params, x = _layer(E=4)
        out = layer.apply({"params": params}, x)
        logits = x.astype(jnp.float32) @ params["gate"]["kernel"]
        probs = jax.nn.softmax(logits, axis=-1)
        sel = np.asarray(jnp.argmax(probs, axis=-1))
        for b in range(x.shape[0]):
            for t in range(x.shape[1]):
                e = sel[b, t]
                h = jax.nn.gelu(x[b, t] @ params["w_in"][e]
                                + params["b_in"][e])
                y = (h @ params["w_out"][e] + params["b_out"][e]) \
                    * probs[b, t, e]
                np.testing.assert_allclose(np.asarray(out[b, t]),
                                           np.asarray(y), atol=1e-5)

    def test_moe_transformer_forward(self):
        model = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                              num_layers=2, max_len=16, num_experts=4)
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 32)
        params = model.init(jax.random.key(0), toks)["params"]
        out = model.apply({"params": params}, toks)
        assert out.shape == (2, 16, 32)
        assert bool(jnp.all(jnp.isfinite(out)))
        assert "moe" in params["block_0"]


class TestExpertParallel:
    @pytest.mark.parametrize("n_ep", [1, 2, 4, 8])
    def test_matches_single_device(self, n_ep):
        layer, params, x = _layer(E=8)
        dense = layer.apply({"params": params}, x)
        mesh = Mesh(np.asarray(jax.devices()[:n_ep]), ("ep",))
        out = ep_moe_apply(params, x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=2e-5, rtol=2e-5)

    def test_rejects_indivisible_experts(self):
        layer, params, x = _layer(E=6)
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("ep",))
        with pytest.raises(ValueError, match="divisible"):
            ep_moe_apply(params, x, mesh)


def test_federated_moe_via_config_surface():
    """moe_experts threads from ModelConfig through define_model into a
    federated round (the CLI path)."""
    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer

    rng = np.random.RandomState(3)
    x = rng.randint(0, 86, (32, 16)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    parts = [np.arange(i * 8, (i + 1) * 8) for i in range(4)]
    data = stack_partitions(x, y, parts)
    cfg = ExperimentConfig(
        data=DataConfig(dataset="shakespeare", batch_size=4),
        federated=FederatedConfig(
            federated=True, num_clients=4, online_client_rate=1.0,
            algorithm="fedavg", sync_type="local_step"),
        model=ModelConfig(arch="transformer", mlp_num_layers=1,
                          rnn_seq_len=16, rnn_hidden_size=8,
                          moe_experts=2),
        optim=OptimConfig(lr=0.05, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        mesh=MeshConfig(num_devices=1),
    ).finalize()
    model = define_model(cfg, batch_size=4)
    assert "moe" in model.init(jax.random.key(0))["block_0"]
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
    server, clients = trainer.init_state(jax.random.key(0))
    server, clients, m = trainer.run_round(server, clients)
    loss = float(m.train_loss.sum() / m.online_mask.sum())
    assert np.isfinite(loss)
