"""MoE layer + expert parallelism (models/transformer.MoEMLP,
parallel/expert.py)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from fedtorch_tpu.models.transformer import MoEMLP, TransformerLM
from fedtorch_tpu.parallel.expert import ep_moe_apply


def _layer(E=8, d=16, B=2, T=12):
    layer = MoEMLP(num_experts=E)
    x = jax.random.normal(jax.random.key(1), (B, T, d))
    params = layer.init(jax.random.key(0), x)["params"]
    return layer, params, x


class TestMoELayer:
    def test_tokens_route_to_argmax_expert(self):
        """Each token's output must equal its top-1 expert's MLP output
        scaled by the gate probability (capacity = all tokens, exact)."""
        layer, params, x = _layer(E=4)
        out = layer.apply({"params": params}, x)
        logits = x.astype(jnp.float32) @ params["gate"]["kernel"]
        probs = jax.nn.softmax(logits, axis=-1)
        sel = np.asarray(jnp.argmax(probs, axis=-1))
        for b in range(x.shape[0]):
            for t in range(x.shape[1]):
                e = sel[b, t]
                h = jax.nn.gelu(x[b, t] @ params["w_in"][e]
                                + params["b_in"][e])
                y = (h @ params["w_out"][e] + params["b_out"][e]) \
                    * probs[b, t, e]
                np.testing.assert_allclose(np.asarray(out[b, t]),
                                           np.asarray(y), atol=1e-5)

    def test_moe_transformer_forward(self):
        model = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                              num_layers=2, max_len=16, num_experts=4)
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 32)
        params = model.init(jax.random.key(0), toks)["params"]
        out = model.apply({"params": params}, toks)
        assert out.shape == (2, 16, 32)
        assert bool(jnp.all(jnp.isfinite(out)))
        assert "moe" in params["block_0"]


class TestExpertParallel:
    @pytest.mark.parametrize("n_ep", [1, 2, 4, 8])
    def test_matches_single_device(self, n_ep):
        layer, params, x = _layer(E=8)
        dense = layer.apply({"params": params}, x)
        mesh = Mesh(np.asarray(jax.devices()[:n_ep]), ("ep",))
        out = ep_moe_apply(params, x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=2e-5, rtol=2e-5)

    def test_rejects_indivisible_experts(self):
        layer, params, x = _layer(E=6)
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("ep",))
        with pytest.raises(ValueError, match="divisible"):
            ep_moe_apply(params, x, mesh)
