"""MoE layer + expert parallelism (models/transformer.MoEMLP,
parallel/expert.py)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from fedtorch_tpu.models.transformer import (
    MoEMLP, TransformerLM, routing_fractions,
)
from fedtorch_tpu.parallel.expert import ep_moe_apply

# ep_moe_apply executes inside jax.shard_map; jax releases that only
# expose jax.experimental.shard_map raise AttributeError before any
# expert math runs — a version skip, not a red baseline. The module's
# single-device MoE-layer tests and the divisibility check (which
# raises before shard_map) stay un-marked.
requires_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="this jax does not expose the public jax.shard_map API "
           "(only jax.experimental.shard_map); ep_moe_apply needs it")


def _layer(E=8, d=16, B=2, T=12):
    layer = MoEMLP(num_experts=E)
    x = jax.random.normal(jax.random.key(1), (B, T, d))
    params = layer.init(jax.random.key(0), x)["params"]
    return layer, params, x


class TestMoELayer:
    def test_tokens_route_to_argmax_expert(self):
        """Each token's output must equal its top-1 expert's MLP output
        scaled by the gate probability (capacity = all tokens, exact)."""
        layer, params, x = _layer(E=4)
        out = layer.apply({"params": params}, x)
        logits = x.astype(jnp.float32) @ params["gate"]["kernel"]
        probs = jax.nn.softmax(logits, axis=-1)
        sel = np.asarray(jnp.argmax(probs, axis=-1))
        for b in range(x.shape[0]):
            for t in range(x.shape[1]):
                e = sel[b, t]
                h = jax.nn.gelu(x[b, t] @ params["w_in"][e]
                                + params["b_in"][e])
                y = (h @ params["w_out"][e] + params["b_out"][e]) \
                    * probs[b, t, e]
                np.testing.assert_allclose(np.asarray(out[b, t]),
                                           np.asarray(y), atol=1e-5)

    def test_moe_transformer_forward(self):
        model = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                              num_layers=2, max_len=16, num_experts=4)
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 32)
        params = model.init(jax.random.key(0), toks)["params"]
        out = model.apply({"params": params}, toks)
        assert out.shape == (2, 16, 32)
        assert bool(jnp.all(jnp.isfinite(out)))
        assert "moe" in params["block_0"]


class TestSparseDispatch:
    """capacity_factor > 0: gather/scatter Switch dispatch
    (transformer.py moe_sparse_compute)."""

    def test_ample_capacity_equals_dense(self):
        """With capacity >= tokens-per-expert no token drops, so the
        sparse path must reproduce the dense one-hot dispatch exactly
        (same per-token expert MLP math, different data movement)."""
        layer, params, x = _layer(E=4)
        dense = layer.apply({"params": params}, x)
        sparse = MoEMLP(num_experts=4, capacity_factor=4.0).apply(
            {"params": params}, x)
        np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                                   atol=1e-6)

    def test_over_capacity_tokens_drop_to_zero(self):
        """Switch §2.2: tokens past an expert's capacity contribute 0
        from the MoE branch (the block's residual passes them through).
        Force every token onto expert 0 via the gate kernel; with
        capacity C only the first C tokens (storage order) survive."""
        layer, params, x = _layer(E=4, B=1, T=8)
        params = dict(params)
        gate_k = np.zeros_like(np.asarray(params["gate"]["kernel"]))
        gate_k[:, 0] = 0.0  # uniform logits -> argmax = expert 0
        params["gate"] = {"kernel": jnp.asarray(gate_k)}
        # capacity_factor 1.0 -> C = ceil(8/4) = 2 per expert
        out = MoEMLP(num_experts=4, capacity_factor=1.0).apply(
            {"params": params}, x)
        out = np.asarray(out[0])
        assert np.abs(out[:2]).max() > 0  # first 2 tokens computed
        np.testing.assert_array_equal(out[2:], 0.0)  # rest dropped

    def test_dropped_tokens_pass_residual_in_block(self):
        """In a full MoE transformer the dropped token's block output
        equals its residual input (plus attention)."""
        model = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                              num_layers=1, max_len=16, num_experts=4,
                              capacity_factor=0.25)
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 32)
        params = model.init(jax.random.key(0), toks)["params"]
        out = model.apply({"params": params}, toks)
        assert bool(jnp.all(jnp.isfinite(out)))


class TestAuxLoss:
    """Switch load-balance aux loss (arXiv:2101.03961 §2.2) + routing
    fraction metrics."""

    def test_uniform_routing_gives_one(self):
        """aux = E * sum_e f_e P_e -> 1 under perfectly uniform routing;
        near-1 for random gates over random tokens."""
        layer, params, x = _layer(E=4, B=4, T=32)
        _, var = layer.apply({"params": params}, x,
                             mutable=["aux_loss"])
        aux = float(var["aux_loss"]["load_balance"][0])
        assert 0.9 < aux < 1.5

    def test_collapsed_routing_approaches_E(self):
        """All tokens on one expert -> f = P ~ onehot -> aux ~ E."""
        E = 4
        layer, params, x = _layer(E=E)
        x = jnp.abs(x) + 0.1            # positive tokens, so that a
        gate_k = np.zeros((x.shape[-1], E), np.float32)
        gate_k[:, 0] = 10.0             # +col-0 kernel always wins
        params = dict(params)
        params["gate"] = {"kernel": jnp.asarray(gate_k)}
        _, var = layer.apply({"params": params}, x,
                             mutable=["aux_loss"])
        aux = float(var["aux_loss"]["load_balance"][0])
        assert aux > 0.9 * E

    def test_aux_loss_is_differentiable_toward_balance(self):
        """The gate gradient of the aux loss must push away from the
        overloaded expert (that is its whole job)."""
        layer, params, x = _layer(E=4)

        def aux_of(p):
            _, var = layer.apply({"params": p}, x, mutable=["aux_loss"])
            return var["aux_loss"]["load_balance"][0]

        g = jax.grad(aux_of)(params)
        assert float(jnp.max(jnp.abs(g["gate"]["kernel"]))) > 0

    def test_routing_fractions_metric(self):
        model = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                              num_layers=2, max_len=16, num_experts=4)
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 32)
        params = model.init(jax.random.key(0), toks)["params"]
        fr = routing_fractions(model, params, toks)
        assert set(fr) == {"block_0", "block_1"}
        for f in fr.values():
            assert f.shape == (4,)
            np.testing.assert_allclose(float(f.sum()), 1.0, atol=1e-5)

    def test_drop_fractions_metric(self):
        """drop_fractions: 0 at ample capacity, >0 at a tight one."""
        from fedtorch_tpu.models.transformer import drop_fractions
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 32)
        ample = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                              num_layers=1, max_len=16, num_experts=4,
                              capacity_factor=4.0)
        params = ample.init(jax.random.key(0), toks)["params"]
        df = drop_fractions(ample, params, toks)
        assert set(df) == {"block_0"}
        assert float(df["block_0"]) == 0.0
        tight = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                              num_layers=1, max_len=16, num_experts=4,
                              capacity_factor=0.25)
        df = drop_fractions(tight, params, toks)
        assert float(df["block_0"]) > 0.0
        # exact dense dispatch sows no drop stat
        dense = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                              num_layers=1, max_len=16, num_experts=4)
        assert drop_fractions(dense, params, toks) == {}

    def test_dense_models_sow_nothing(self):
        model = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                              num_layers=1, max_len=16)
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 32)
        params = model.init(jax.random.key(0), toks)["params"]
        assert routing_fractions(model, params, toks) == {}


class TestExpertParallel:
    @requires_shard_map
    @pytest.mark.parametrize("n_ep", [1, 2, 4, 8])
    def test_matches_single_device(self, n_ep):
        layer, params, x = _layer(E=8)
        dense = layer.apply({"params": params}, x)
        mesh = Mesh(np.asarray(jax.devices()[:n_ep]), ("ep",))
        out = ep_moe_apply(params, x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=2e-5, rtol=2e-5)

    @requires_shard_map
    @pytest.mark.parametrize("n_ep", [2, 8])
    def test_sparse_dispatch_matches_dense(self, n_ep):
        """EP sparse path (per-device token gather over the expert
        shard) == single-device dense output at ample capacity."""
        layer, params, x = _layer(E=8)
        dense = layer.apply({"params": params}, x)
        mesh = Mesh(np.asarray(jax.devices()[:n_ep]), ("ep",))
        out = ep_moe_apply(params, x, mesh, capacity_factor=8.0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   atol=2e-5, rtol=2e-5)

    @requires_shard_map
    def test_sparse_dispatch_matches_module_sparse_with_drops(self):
        """With a TIGHT capacity the EP sparse path must drop exactly
        the tokens the single-device sparse module drops."""
        layer, params, x = _layer(E=8, B=2, T=12)
        cf = 0.5
        ref = MoEMLP(num_experts=8, capacity_factor=cf).apply(
            {"params": params}, x)
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("ep",))
        out = ep_moe_apply(params, x, mesh, capacity_factor=cf)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_rejects_indivisible_experts(self):
        layer, params, x = _layer(E=6)
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("ep",))
        with pytest.raises(ValueError, match="divisible"):
            ep_moe_apply(params, x, mesh)


def test_federated_moe_via_config_surface():
    """moe_experts threads from ModelConfig through define_model into a
    federated round (the CLI path)."""
    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer

    rng = np.random.RandomState(3)
    x = rng.randint(0, 86, (32, 16)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    parts = [np.arange(i * 8, (i + 1) * 8) for i in range(4)]
    data = stack_partitions(x, y, parts)
    cfg = ExperimentConfig(
        data=DataConfig(dataset="shakespeare", batch_size=4),
        federated=FederatedConfig(
            federated=True, num_clients=4, online_client_rate=1.0,
            algorithm="fedavg", sync_type="local_step"),
        model=ModelConfig(arch="transformer", mlp_num_layers=1,
                          rnn_seq_len=16, rnn_hidden_size=8,
                          moe_experts=2),
        optim=OptimConfig(lr=0.05, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        mesh=MeshConfig(num_devices=1),
    ).finalize()
    model = define_model(cfg, batch_size=4)
    assert "moe" in model.init(jax.random.key(0))["block_0"]
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
    server, clients = trainer.init_state(jax.random.key(0))
    server, clients, m = trainer.run_round(server, clients)
    loss = float(m.train_loss.sum() / m.online_mask.sum())
    assert np.isfinite(loss)


def test_federated_moe_sparse_with_aux_loss():
    """Sparse dispatch + Switch aux loss thread through the engine: the
    aux term must actually enter the training loss (losses with weight
    on differ from weight off) and stay finite."""
    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer

    rng = np.random.RandomState(3)
    x = rng.randint(0, 86, (32, 16)).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    parts = [np.arange(i * 8, (i + 1) * 8) for i in range(4)]
    data = stack_partitions(x, y, parts)

    def run(aux_w):
        cfg = ExperimentConfig(
            data=DataConfig(dataset="shakespeare", batch_size=4),
            federated=FederatedConfig(
                federated=True, num_clients=4, online_client_rate=1.0,
                algorithm="fedavg", sync_type="local_step"),
            model=ModelConfig(arch="transformer", mlp_num_layers=1,
                              rnn_seq_len=16, rnn_hidden_size=8,
                              moe_experts=2, moe_capacity_factor=1.5,
                              moe_aux_weight=aux_w),
            optim=OptimConfig(lr=0.05, weight_decay=0.0),
            train=TrainConfig(local_step=2),
            mesh=MeshConfig(num_devices=1),
        ).finalize()
        model = define_model(cfg, batch_size=4)
        assert model.has_aux_loss
        trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
        server, clients = trainer.init_state(jax.random.key(0))
        _, _, m = trainer.run_round(server, clients)
        return float(m.train_loss.sum() / m.online_mask.sum())

    base, with_aux = run(0.0), run(0.1)
    assert np.isfinite(base) and np.isfinite(with_aux)
    # the reported loss includes the aux term only when weighted in
    assert abs(with_aux - base) > 1e-6
