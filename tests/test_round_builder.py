"""Round-program builder composition matrix (ISSUE 11).

One parametrized sweep over EVERY (source x dispatch x execution) cell
of ``parallel/round_program.py`` — enumerated from the module's own
axis tuples, so a new axis value can never be silently absent. Each
cell asserts exactly one of:

* **legal** — the cell's per-round trajectory (server params, full
  client state, metrics) is BITWISE-identical to the per-round device
  program with the same execution strategy, and the cell's program
  traces exactly once (the two engine-wide bars); commit cells, whose
  semantics differ from the sync round by design (staleness,
  snapshot bases), instead pin cross-source bitwise parity against
  the resident commit program plus determinism and trace-once;
* **illegal** — ONE ``ValueError`` naming the cell, raised from the
  single validator (construction for round/commit, the ``run_rounds``
  call for scan — the deferred gate).

The chaos/guard composition of the NEW cell (the scanned streamed
program) is pinned here too: chaos + guards ride ``_round_core``, so
the faulted feed x scan trajectory must equal the faulted per-round
device one bitwise.
"""
import re

import jax
import numpy as np
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
    MeshConfig, ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.data.batching import stack_partitions
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.parallel.round_program import (
    DISPATCHES, EXECUTIONS, SOURCES, cell_name, illegal_reason,
    iter_cells,
)
from fedtorch_tpu.utils.tracing import RecompilationSentinel

CELLS = list(iter_cells())
# the genuinely impossible cells of the base (fedavg) matrix — every
# other combination must run and hold the parity bars
ILLEGAL = {
    ("resident", "commit", "fused"),
    ("feed", "commit", "fused"),
}

CHAOS = {"client_drop_rate": 0.3, "straggler_rate": 0.3,
         "nan_inject_rate": 0.3, "guard_updates": True}


def make_cfg(source, *, execution="vmap", sync_mode="sync",
             algorithm="fedavg", fault_kw=None, **fed_kw):
    plane = "stream" if source == "feed" else "device"
    if execution == "fused":
        # the fused execution needs a fused module (cnn/bn) and a
        # single-device mesh; conv_impl pinned for the same-lowering
        # A/B contract (tests/test_client_fusion.py)
        return ExperimentConfig(
            data=DataConfig(dataset="cifar10", batch_size=6,
                            augment=False, data_plane=plane),
            federated=FederatedConfig(
                federated=True, num_clients=4, online_client_rate=0.5,
                algorithm=algorithm, sync_type="local_step",
                sync_mode=sync_mode, **fed_kw),
            model=ModelConfig(arch="cnn", conv_impl="conv", norm="bn"),
            optim=OptimConfig(lr=0.05, in_momentum=True),
            train=TrainConfig(local_step=2),
            mesh=MeshConfig(num_devices=1, client_fusion=execution),
            fault=FaultConfig(**(fault_kw or {})),
        ).finalize()
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20,
                        batch_size=16, synthetic_alpha=0.5,
                        synthetic_beta=0.5, data_plane=plane),
        federated=FederatedConfig(
            federated=True, num_clients=12, online_client_rate=0.5,
            algorithm=algorithm, sync_type="local_step",
            sync_mode=sync_mode, **fed_kw),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.3, weight_decay=0.0),
        train=TrainConfig(local_step=3),
        mesh=MeshConfig(client_fusion=execution),
        fault=FaultConfig(**(fault_kw or {})),
    ).finalize()


def build_trainer(source, *, execution="vmap", dispatch="round",
                  fault_kw=None, algorithm="fedavg", **fed_kw):
    sync_mode = "async" if dispatch == "commit" else "sync"
    cfg = make_cfg(source, execution=execution, sync_mode=sync_mode,
                   algorithm=algorithm, fault_kw=fault_kw, **fed_kw)
    if execution == "fused":
        sizes = (24, 9, 17, 24)
        rng = np.random.RandomState(0)
        feats = rng.randn(sum(sizes), 32, 32, 3).astype(np.float32)
        labels = rng.randint(0, 10, sum(sizes))
        off = np.concatenate([[0], np.cumsum(sizes)])
        parts = [np.arange(off[i], off[i + 1])
                 for i in range(len(sizes))]
        data = stack_partitions(feats, labels, parts)
    else:
        data = build_federated_data(cfg).train
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    if sync_mode == "async":
        from fedtorch_tpu.async_plane import AsyncFederatedTrainer
        return AsyncFederatedTrainer(cfg, model, make_algorithm(cfg),
                                     data)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data)


def assert_trees_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def stack_metrics(ms):
    return jax.tree.map(lambda *xs: np.stack([np.asarray(x)
                                              for x in xs]), *ms)


def run_cell(trainer, dispatch, rounds=4, seed=3, chunk=2):
    """Run ``rounds`` rounds/commits through the cell's dispatch and
    return (server, clients, stacked per-round metrics)."""
    server, clients = trainer.init_state(jax.random.key(seed))
    if dispatch == "scan":
        all_ms = []
        for _ in range(rounds // chunk):
            server, clients, ms = trainer.run_rounds(server, clients,
                                                     chunk)
            all_ms.append(jax.tree.map(np.asarray, ms))
        metrics = jax.tree.map(
            lambda *xs: np.concatenate(xs, axis=0), *all_ms)
    else:
        per_round = []
        for _ in range(rounds):
            server, clients, m = trainer.run_round(server, clients)
            per_round.append(m)
        metrics = stack_metrics(per_round)
    trainer.invalidate_stream()
    return server, clients, metrics


def cell_trace_name(trainer, source, dispatch, chunk=2):
    if dispatch == "round":
        return trainer.trace_name if source == "resident" \
            else trainer.stream_trace_name
    if dispatch == "commit":
        return trainer.commit_trace_name if source == "resident" \
            else trainer.commit_stream_trace_name
    suffix = "" if source == "resident" else "_stream"
    return (f"federated.rounds{suffix}"
            f"[{trainer.algorithm.name}]x{chunk}")


@pytest.mark.parametrize("source,dispatch,execution", CELLS)
def test_matrix_cell_parity_or_named_refusal(source, dispatch,
                                             execution):
    cell = (source, dispatch, execution)
    if cell in ILLEGAL:
        with pytest.raises(ValueError,
                           match=re.escape(cell_name(*cell))):
            t = build_trainer(source, execution=execution,
                              dispatch=dispatch)
            if dispatch == "scan":  # deferred gate (never reached here)
                s, c = t.init_state(jax.random.key(0))
                t.run_rounds(s, c, 2)
        return

    trainer = build_trainer(source, execution=execution,
                            dispatch=dispatch)
    with RecompilationSentinel() as sentinel:
        server, clients, metrics = run_cell(trainer, dispatch)
        jax.block_until_ready(jax.tree.leaves(server.params))
    sentinel.assert_traces(
        cell_trace_name(trainer, source, dispatch), expected=1)

    if dispatch == "commit":
        # commit semantics differ from the sync round by design; the
        # bar is cross-source bitwise parity against the resident
        # commit program (the per-commit device program)
        ref = build_trainer("resident", execution=execution,
                            dispatch="commit")
        rs, rc, rm = run_cell(ref, "commit")
        assert_trees_equal((server.params, server.aux, clients),
                           (rs.params, rs.aux, rc))
        assert_trees_equal(metrics, rm)
        return

    # round/scan: bitwise parity with the per-round DEVICE program of
    # the same execution strategy — the engine-wide reference
    ref = build_trainer("resident", execution=execution,
                        dispatch="round")
    rs, rc, rm = run_cell(ref, "round")
    assert_trees_equal((server.params, server.aux, clients),
                       (rs.params, rs.aux, rc))
    assert_trees_equal(metrics, rm)


def test_scanned_stream_composes_with_chaos_and_guards():
    """The NEW cell (feed x scan): chaos crashes/stragglers/poison +
    update guards ride _round_core, so the faulted scanned-stream
    trajectory must equal the faulted per-round device one bitwise."""
    t_ref = build_trainer("resident", fault_kw=CHAOS)
    t_new = build_trainer("feed", fault_kw=CHAOS)
    rs, rc, rm = run_cell(t_ref, "round")
    ss, sc, sm = run_cell(t_new, "scan")
    assert_trees_equal((rs.params, rs.aux, rc), (ss.params, ss.aux, sc))
    assert_trees_equal(rm, sm)
    # the faulted rounds actually exercised the fault path
    assert float(np.sum(np.asarray(sm.dropped_clients))) > 0


def test_run_rounds_refuses_zero_rounds_before_consuming_feeds():
    """run_rounds(.., 0) must refuse BEFORE touching the producer: a
    zero-length scan would trace to an obscure shape error, and on
    the stream plane it would first pop (and lose) a real feed —
    silently desyncing the producer from the device round."""
    t = build_trainer("feed")
    server, clients = t.init_state(jax.random.key(0))
    with pytest.raises(ValueError, match="num_rounds"):
        t.run_rounds(server, clients, 0)
    assert t._stream is None  # no producer was started, nothing lost
    # the trainer is still healthy: a real round runs fine after
    server, clients, _ = t.run_round(server, clients)
    t.invalidate_stream()


def test_scan_cell_refused_on_async_at_call_time():
    """The deferred scan gate: an async trainer CONSTRUCTS fine and
    run_rounds raises the one cell-named ValueError at call time —
    commits are host-scheduled events, nothing to scan."""
    t = build_trainer("resident", dispatch="commit")
    server, clients = t.init_state(jax.random.key(0))
    with pytest.raises(ValueError, match="run_rounds"):
        t.run_rounds(server, clients, 2)
    with pytest.raises(ValueError, match=re.escape(
            cell_name("resident", "scan", "vmap"))):
        t.run_rounds(server, clients, 2)


@pytest.mark.parametrize("source,dispatch,algorithm,fed_kw,match", [
    # qFFL (shard feed layout) and default-uniform DRFA (host probe
    # plan) now RUN on the feed source — the remaining feed refusal is
    # the lambda-DISTRIBUTED draw, which reads device state (the dual
    # variable) the host feed builder cannot see
    ("feed", "round", "fedavg",
     {"drfa": True, "drfa_lambda_sampling": True}, "participation"),
    ("resident", "commit", "qsparse", {},
     "sync_mode='async' is unsupported"),
    ("feed", "commit", "afl", {},
     "sync_mode='async' is unsupported"),
])
def test_algorithm_precondition_cells_raise_named(source, dispatch,
                                                  algorithm, fed_kw,
                                                  match):
    """Axis-precondition refusals (algorithm families an axis value
    cannot serve) raise the same cell-named ValueError as the
    structural cells — one error site for the whole matrix."""
    with pytest.raises(ValueError) as err:
        build_trainer(source, dispatch=dispatch, algorithm=algorithm,
                      **fed_kw)
    assert re.search(match, str(err.value))
    assert "round-program cell" in str(err.value)


# -- refusal-message snapshots (the gate matrix is user-facing API) -------
# One test per structurally illegal cell pinning the EXACT ValueError
# text, so refusal wording cannot silently regress. The registry-drift
# checker (fedtorch_tpu.lint.registry_audit, FTC005) requires each
# illegal cell's name to appear here next to the ILLEGAL set.

def _validate(source, dispatch, execution, sync_mode):
    cfg = make_cfg(source, execution=execution, sync_mode=sync_mode)
    alg = make_algorithm(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    from fedtorch_tpu.parallel.round_program import validate_cell
    validate_cell(source, dispatch, execution, cfg=cfg, algorithm=alg,
                  model=model, mesh_devices=1, k_online=2,
                  gather_mode="auto", has_val=False)


_COMMIT_FUSED_REASON = (
    "client_fusion='fused' packs clients into one grouped conv "
    "against ONE shared server snapshot; buffered commits train each "
    "client against its own dispatch-time version — use the vmap "
    "execution or --sync_mode sync")


def test_refusal_snapshot_resident_commit_fused():
    with pytest.raises(ValueError) as err:
        _validate("resident", "commit", "fused", "async")
    assert str(err.value) == (
        "round-program cell (resident x commit x fused) is "
        "unsupported here: " + _COMMIT_FUSED_REASON)


def test_refusal_snapshot_feed_commit_fused():
    with pytest.raises(ValueError) as err:
        _validate("feed", "commit", "fused", "async")
    assert str(err.value) == (
        "round-program cell (feed x commit x fused) is "
        "unsupported here: " + _COMMIT_FUSED_REASON)


def test_refusal_snapshot_scan_under_async():
    """The deferred scan gate's exact text (run_rounds on the async
    plane) — structurally impossible like the fused commits, but
    refused at call time rather than construction."""
    with pytest.raises(ValueError) as err:
        _validate("resident", "scan", "vmap", "async")
    assert str(err.value) == (
        "round-program cell (resident x scan x vmap) is unsupported "
        "here: run_rounds scans ONE traced round program over R "
        "rounds' inputs, but async commits are host-scheduled events "
        "(each commit's jobs come from the event scheduler), so no "
        "R-commit program exists to scan — call run_round once per "
        "commit, or use --sync_mode sync for the scan dispatch")


def test_matrix_has_no_silently_absent_cells():
    """Every combination of the module's axis tuples is either in this
    file's ILLEGAL set (and refused by the validator) or reaches a
    runnable program — the parametrization above covers the full
    product, and the validator agrees with ILLEGAL on the base
    config."""
    assert len(CELLS) == len(SOURCES) * len(DISPATCHES) * len(EXECUTIONS)
    for source, dispatch, execution in CELLS:
        sync_mode = "async" if dispatch == "commit" else "sync"
        cfg = make_cfg(source, execution=execution, sync_mode=sync_mode)
        alg = make_algorithm(cfg)
        model = define_model(cfg, batch_size=cfg.data.batch_size)
        reason = illegal_reason(
            source, dispatch, execution, cfg=cfg, algorithm=alg,
            model=model, mesh_devices=1, k_online=2,
            gather_mode="auto", has_val=False)
        expected_illegal = (source, dispatch, execution) in ILLEGAL
        assert (reason is not None) == expected_illegal, (
            (source, dispatch, execution), reason)
