"""Test harness: run everything on a virtual 8-device CPU mesh.

This is the JAX-native analog of the reference's 'centered mode' fake
backend (SURVEY.md §4): all collective code paths execute in CI without a
TPU by forcing the host platform to expose 8 devices.

Must run before jax is imported anywhere.
"""
import os

# Force CPU even when the ambient environment selects a TPU platform
# (e.g. JAX_PLATFORMS=axon): the test mesh is always the virtual host mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# A TPU-proxy sitecustomize hook (if present) may override jax_platforms
# to "<proxy>,cpu" at interpreter start, which would make every test pay a
# slow (or hung) remote-device handshake. Undo it before any jax backend
# initializes — at conftest import time none has.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# -- Suite tiering ----------------------------------------------------------
# tests/slow_tests.txt lists nodeids measured >= the threshold on the
# 1-core reference box (scripts/tier_tests.py regenerates it from a
# --durations=0 log). They get the `slow` marker automatically, so
#   pytest -m "not slow"    is the fast lane (<5 min on that box)
#   pytest tests/           still runs everything.
# Explicit @pytest.mark.slow decorations (multi-process tests) remain.
_SLOW_LIST = os.path.join(os.path.dirname(__file__), "slow_tests.txt")


def _slow_nodeids():
    try:
        with open(_SLOW_LIST) as f:
            return {line.split("#", 1)[0].strip() for line in f
                    if line.strip() and not line.startswith("#")}
    except OSError:
        return set()


def _advise(config, msg):
    """Print an advisory without the warnings machinery: under a
    project/user ``filterwarnings = error`` a collection-time
    ``warnings.warn`` would abort collection of the whole suite, and a
    degraded fast lane must never cost the full one."""
    import sys
    tr = config.pluginmanager.get_plugin("terminalreporter")
    if tr is not None:
        tr.write_line("conftest: " + msg, yellow=True)
    else:
        print("conftest: " + msg, file=sys.stderr)


def pytest_collection_modifyitems(config, items):
    slow = _slow_nodeids()
    if not slow:
        _advise(config, "tests/slow_tests.txt missing or empty — the "
                "fast lane (-m 'not slow') will run slow tests; "
                "regenerate with scripts/tier_tests.py")
        return
    matched = set()
    for item in items:
        if item.nodeid in slow:
            matched.add(item.nodeid)
            item.add_marker(pytest.mark.slow)
    # surface staleness: a renamed test or changed parametrize id would
    # otherwise silently re-enter the fast lane. Only judge entries
    # whose FILE was collected in this run (path-restricted runs never
    # warn spuriously), and skip entirely when the invocation selects
    # individual node ids or deselects tests — then partial matches
    # are expected, not stale.
    if any("::" in a for a in config.args) \
            or config.getoption("deselect", None) \
            or config.getoption("keyword", None):
        return
    collected_files = {item.nodeid.split("::", 1)[0] for item in items}
    unmatched = {s for s in slow - matched
                 if s.split("::", 1)[0] in collected_files}
    if unmatched:
        _advise(config, f"{len(unmatched)} entries in tests/slow_tests.txt "
                "match no collected test (stale after a rename?); "
                "regenerate with scripts/tier_tests.py: "
                + ", ".join(sorted(unmatched)[:3]) + " ...")
