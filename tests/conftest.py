"""Test harness: run everything on a virtual 8-device CPU mesh.

This is the JAX-native analog of the reference's 'centered mode' fake
backend (SURVEY.md §4): all collective code paths execute in CI without a
TPU by forcing the host platform to expose 8 devices.

Must run before jax is imported anywhere.
"""
import os

# Force CPU even when the ambient environment selects a TPU platform
# (e.g. JAX_PLATFORMS=axon): the test mesh is always the virtual host mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# A TPU-proxy sitecustomize hook (if present) may override jax_platforms
# to "<proxy>,cpu" at interpreter start, which would make every test pay a
# slow (or hung) remote-device handshake. Undo it before any jax backend
# initializes — at conftest import time none has.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
