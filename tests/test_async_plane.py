"""Async commit plane (fedtorch_tpu.async_plane) — fast-lane tests.

Covers the ISSUE 6 test satellites: staleness-weight math (const/poly/
inv, weight 1 at staleness 0, composition with the guard
renormalization), the deterministic event scheduler (same seed →
identical commit sequences, fast-forward == stepped, ring clamping,
tail-independence of the commit clock), trainer-level bitwise
determinism and device/stream parity, the trace-once sentinel on the
commit program, and checkpoint-resume bitwise parity. The sync-vs-async
CONVERGENCE bar runs in the slow lane (tests/test_chaos_suite.py
straggler-heavy case); the CLI drain drill extends
tests/test_preemption.py.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.async_plane import (
    ASYNC_ALGORITHMS, AsyncFederatedTrainer,
)
from fedtorch_tpu.async_plane.scheduler import (
    AsyncSchedule, simulate_sync_round_times,
)
from fedtorch_tpu.async_plane.staleness import (
    STALENESS_MODES, normalized_staleness_weights, staleness_weight,
)
from fedtorch_tpu.config import (
    CheckpointConfig, DataConfig, ExperimentConfig, FaultConfig,
    FederatedConfig, ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.robustness.guards import renormalize_accepted
from fedtorch_tpu.utils.tracing import RecompilationSentinel

STRAGGLER_HEAVY = {"straggler_rate": 0.4, "straggler_step_frac": 0.1}


def make_cfg(algorithm="fedavg", plane="device", sync_mode="async",
             num_clients=12, num_comms=4, fault_kw=None, fed_kw=None,
             **ckpt_kw):
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=10,
                        batch_size=8, data_plane=plane),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients,
            num_comms=num_comms, online_client_rate=0.5,
            algorithm=algorithm, sync_type="local_step",
            sync_mode=sync_mode, **(fed_kw or {})),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.5, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        fault=FaultConfig(**(fault_kw if fault_kw is not None
                             else STRAGGLER_HEAVY)),
        checkpoint=CheckpointConfig(**ckpt_kw),
    ).finalize()


def make_trainer(cfg):
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    cls = AsyncFederatedTrainer if cfg.federated.sync_mode == "async" \
        else __import__("fedtorch_tpu.parallel",
                        fromlist=["FederatedTrainer"]).FederatedTrainer
    return cls(cfg, model, make_algorithm(cfg), data.train)


def run_commits(trainer, n, seed=0, collect=False):
    server, clients = trainer.init_state(jax.random.key(seed))
    traj = []
    for _ in range(n):
        server, clients, m = trainer.run_round(server, clients)
        if collect:
            traj.append(np.concatenate([
                np.ravel(x) for x in jax.tree.leaves(
                    jax.device_get(server.params))]))
    trainer.invalidate_stream()
    return server, clients, m, traj


# -- staleness weights -------------------------------------------------------
class TestStalenessWeights:
    def test_weight_is_one_at_zero_staleness(self):
        for mode in STALENESS_MODES:
            w = staleness_weight(jnp.zeros(4), mode, exponent=0.5)
            np.testing.assert_array_equal(np.asarray(w), np.ones(4))

    def test_shapes_hand_computed(self):
        tau = jnp.asarray([0.0, 1.0, 3.0])
        np.testing.assert_array_equal(
            np.asarray(staleness_weight(tau, "const")), np.ones(3))
        np.testing.assert_allclose(
            np.asarray(staleness_weight(tau, "poly", 0.5)),
            [1.0, 2.0 ** -0.5, 0.5], rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(staleness_weight(tau, "inv")),
            [1.0, 0.5, 0.25], rtol=1e-6)
        # inv is poly at exponent 1 — one family
        np.testing.assert_allclose(
            np.asarray(staleness_weight(tau, "inv")),
            np.asarray(staleness_weight(tau, "poly", 1.0)), rtol=1e-6)

    def test_normalized_mean_is_one(self):
        tau = jnp.asarray([0.0, 2.0, 5.0, 1.0])
        for mode in STALENESS_MODES:
            w = normalized_staleness_weights(tau, mode, 0.5)
            assert float(jnp.mean(w)) == pytest.approx(1.0, rel=1e-6)

    def test_all_fresh_commit_reproduces_sync_weighting(self):
        # tau == 0 everywhere → multiplier exactly 1: the async
        # aggregation degenerates to the sync round's
        for mode in STALENESS_MODES:
            w = normalized_staleness_weights(jnp.zeros(5), mode)
            np.testing.assert_array_equal(np.asarray(w), np.ones(5))

    def test_unknown_mode_raises(self):
        with pytest.raises(ValueError, match="staleness_weight"):
            staleness_weight(jnp.zeros(2), "linear")

    def test_composes_with_guard_renormalization(self):
        """A REJECTED stale update hands back exactly its DAMPED
        weight: the renormalization operates on the composed weights
        (base x staleness), hand-computed here."""
        base = jnp.asarray([0.25, 0.25, 0.5])
        scale = normalized_staleness_weights(
            jnp.asarray([0.0, 4.0, 1.0]), "inv")
        weights = base * scale
        accept = jnp.asarray([1.0, 0.0, 1.0])  # reject the stale one
        payload_sum = {"w": jnp.asarray([2.0])}
        out = renormalize_accepted(payload_sum, weights, accept)
        expected = 2.0 * float(jnp.sum(weights)) / float(
            jnp.sum(weights * accept))
        assert float(out["w"][0]) == pytest.approx(expected, rel=1e-6)
        # and the damped weight is genuinely smaller than the fresh
        # one would have been — rejecting a stale update costs less
        assert float(weights[1]) < float(base[1])


# -- the event scheduler -----------------------------------------------------
def _sched(start_commit=0, ring=8, num_clients=16, concurrency=6,
           buffer_size=3, seed=7, **kw):
    key = jax.random.key(seed)
    key_data = np.asarray(jax.device_get(jax.random.key_data(key)))
    return AsyncSchedule(
        key_data, jax.random.key_impl(key), num_clients=num_clients,
        concurrency=concurrency, buffer_size=buffer_size,
        ring_size=ring, start_commit=start_commit,
        **{**STRAGGLER_HEAVY, **kw})


class TestAsyncSchedule:
    def test_same_seed_identical_commit_sequence(self):
        a, b = _sched(), _sched()
        for _ in range(6):
            pa, pb = a.next_commit(), b.next_commit()
            assert pa.commit == pb.commit
            np.testing.assert_array_equal(pa.idx, pb.idx)
            np.testing.assert_array_equal(pa.version, pb.version)
            np.testing.assert_array_equal(pa.dispatch, pb.dispatch)
            np.testing.assert_array_equal(pa.arrival_times,
                                          pb.arrival_times)

    def test_fast_forward_equals_stepped(self):
        """start_commit=N is the resume path: a fresh instance
        fast-forwarded to commit N must continue exactly like the
        original instance that lived through commits 0..N-1."""
        live = _sched()
        for _ in range(4):
            live.next_commit()
        resumed = _sched(start_commit=4)
        for _ in range(3):
            pl, pr = live.next_commit(), resumed.next_commit()
            assert pl.commit == pr.commit
            np.testing.assert_array_equal(pl.idx, pr.idx)
            np.testing.assert_array_equal(pl.version, pr.version)
            np.testing.assert_array_equal(pl.dispatch, pr.dispatch)

    def test_commit_plan_invariants(self):
        s = _sched()
        for expected_commit in range(5):
            p = s.next_commit()
            assert p.commit == expected_commit
            # distinct clients, all in range
            assert len(set(p.idx.tolist())) == len(p.idx)
            assert (p.idx >= 0).all() and (p.idx < 16).all()
            # no update trains on the future; arrivals are ordered
            assert (p.version <= p.commit).all()
            assert (np.diff(p.arrival_times) >= 0).all()
            assert p.commit_time == p.arrival_times[-1]

    def test_ring_clamp_counted(self):
        """A 2-deep ring under a heavy tail must clamp some arrivals
        to the oldest retained snapshot (and count them)."""
        s = _sched(ring=2)
        for _ in range(12):
            p = s.next_commit()
            assert (p.version >= max(p.commit - 1, 0)).all()
        assert s.stats.staleness_clamped > 0

    def test_stats_count_stragglers(self):
        s = _sched()
        for _ in range(8):
            s.next_commit()
        st = s.stats
        assert st.dispatches >= 6 + 8 * 3  # cohort + replacements
        assert 0 < st.stragglers < st.dispatches

    def test_commit_clock_not_gated_on_tail(self):
        """The A/B's claim at scheduler level: under the same delay
        model, the async commit interval (fastest m of the in-flight
        cohort) beats the sync round interval (max over k)."""
        s = _sched()
        n = 20
        for _ in range(n):
            s.next_commit()
        commit_dt = s.commit_times[-1] / n
        key = jax.random.key(7)
        rounds = simulate_sync_round_times(
            np.asarray(jax.device_get(jax.random.key_data(key))),
            jax.random.key_impl(key), rounds=n, k_online=6,
            **STRAGGLER_HEAVY)
        assert commit_dt < float(np.mean(rounds))

    def test_population_guard(self):
        with pytest.raises(ValueError, match="num_clients"):
            _sched(num_clients=8, concurrency=6, buffer_size=3)


# -- the trainer -------------------------------------------------------------
class TestAsyncTrainer:
    def test_same_seed_bitwise_commit_sequence(self):
        cfg = make_cfg()
        t1, t2 = make_trainer(cfg), make_trainer(cfg)
        *_, a = run_commits(t1, 4, collect=True)
        *_, b = run_commits(t2, 4, collect=True)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    @pytest.mark.parametrize("algorithm", ["fedavg", "scaffold"])
    def test_device_stream_parity_bitwise(self, algorithm):
        """The two async data planes run the same commit program —
        the host feed producer replays the device row plan exactly."""
        td = make_trainer(make_cfg(algorithm, plane="device"))
        ts = make_trainer(make_cfg(algorithm, plane="stream"))
        *_, a = run_commits(td, 4, collect=True)
        *_, b = run_commits(ts, 4, collect=True)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_staleness_metric_reported(self):
        tr = make_trainer(make_cfg())
        _, _, m, _ = run_commits(tr, 2)
        assert float(m.staleness_mean) > 0.0
        assert float(m.straggler_clients) >= 0.0

    def test_sync_plane_reports_zero_staleness(self):
        cfg = make_cfg(sync_mode="sync", fault_kw={})
        tr = make_trainer(cfg)
        _, _, m, _ = run_commits(tr, 2)
        assert float(jnp.asarray(m.staleness_mean)) == 0.0

    def test_commit_program_traces_once(self):
        tr = make_trainer(make_cfg(num_comms=4))
        server, clients = tr.init_state(jax.random.key(0))
        with RecompilationSentinel() as s:
            for _ in range(4):
                server, clients, _ = tr.run_round(server, clients)
        tr.invalidate_stream()
        s.assert_traces(tr.commit_trace_name, expected=1)

    def test_resumed_run_matches_uninterrupted_bitwise(self, tmp_path):
        """Kill-drill core (in-process): checkpoint at commit 3,
        rebuild everything from disk, run 3 more — the stitched
        trajectory must equal the uninterrupted 6-commit run bitwise
        (the scheduler fast-forwards its event simulation to the
        checkpoint's commit)."""
        from fedtorch_tpu.utils import maybe_resume, save_checkpoint

        cfg = make_cfg(num_comms=6)
        ref, *_ = run_commits(make_trainer(cfg), 6)

        tr = make_trainer(cfg)
        server, clients = tr.init_state(jax.random.key(0))
        for _ in range(3):
            server, clients, _ = tr.run_round(server, clients)
        save_checkpoint(str(tmp_path), server, clients, cfg, 0.0, False)
        tr.invalidate_stream()
        del tr, server, clients

        tr2 = make_trainer(cfg)
        server, clients = tr2.init_state(jax.random.key(0))
        server, clients, _, resumed = maybe_resume(
            str(tmp_path), server, clients, cfg)
        assert resumed and int(jax.device_get(server.round)) == 3
        for _ in range(3):
            server, clients, _ = tr2.run_round(server, clients)
        tr2.invalidate_stream()
        assert int(jax.device_get(server.round)) == 6
        for a, b in zip(jax.tree.leaves(ref.params),
                        jax.tree.leaves(server.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_supervisor_rollback_resyncs_scheduler(self):
        """invalidate_stream (the supervisor's rollback hook) drops
        the event scheduler; the next commit rebuilds it from the live
        (rng, round) state and the trajectory continues unchanged."""
        cfg = make_cfg(num_comms=4)
        ref, *_ = run_commits(make_trainer(cfg), 4)
        tr = make_trainer(cfg)
        server, clients = tr.init_state(jax.random.key(0))
        for i in range(4):
            server, clients, _ = tr.run_round(server, clients)
            if i == 1:
                tr.invalidate_stream()  # mid-run resync
        tr.invalidate_stream()
        for a, b in zip(jax.tree.leaves(ref.params),
                        jax.tree.leaves(server.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- config / checkpoint surface ---------------------------------------------
class TestAsyncConfigSurface:
    def test_sync_mode_validated(self):
        with pytest.raises(ValueError, match="sync_mode"):
            make_cfg(sync_mode="buffered")

    def test_async_requires_federated(self):
        with pytest.raises(ValueError, match="federated=True"):
            ExperimentConfig(
                federated=FederatedConfig(federated=False,
                                          sync_mode="async"),
            ).finalize()

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="staleness_exponent"):
            make_cfg(fed_kw={"staleness_exponent": 0.0})
        with pytest.raises(ValueError, match="snapshot_ring"):
            make_cfg(fed_kw={"snapshot_ring": 1})
        with pytest.raises(ValueError, match="async_buffer_size"):
            make_cfg(fed_kw={"async_buffer_size": -1})
        with pytest.raises(ValueError, match="staleness_weight"):
            make_cfg(fed_kw={"staleness_weight": "exp"})

    def test_cli_flags_map(self):
        from fedtorch_tpu.cli import args_to_config, build_parser
        cfg = args_to_config(build_parser().parse_args([
            "--federated", "true", "-d", "synthetic", "-a",
            "logistic_regression", "--sync_mode", "async",
            "--async_buffer_size", "4", "--async_concurrency", "9",
            "--staleness_weight", "inv", "--staleness_exponent", "0.7",
            "--snapshot_ring", "5"]))
        fed = cfg.federated
        assert fed.sync_mode == "async"
        assert fed.async_buffer_size == 4
        assert fed.async_concurrency == 9
        assert fed.staleness_weight == "inv"
        assert fed.staleness_exponent == 0.7
        assert fed.snapshot_ring == 5

    def test_checkpoint_refuses_cross_plane_resume(self, tmp_path):
        """A sync checkpoint must not silently resume an async run (the
        ring wrap makes the aux STRUCTURALLY different): the compat
        check names sync_mode."""
        from fedtorch_tpu.utils import maybe_resume, save_checkpoint

        cfg = make_cfg(sync_mode="sync", fault_kw={})
        tr = make_trainer(cfg)
        server, clients = tr.init_state(jax.random.key(0))
        save_checkpoint(str(tmp_path), server, clients, cfg, 0.0, False)

        acfg = make_cfg(sync_mode="async", fault_kw={})
        tr2 = make_trainer(acfg)
        server2, clients2 = tr2.init_state(jax.random.key(0))
        with pytest.raises(ValueError, match="sync_mode"):
            maybe_resume(str(tmp_path), server2, clients2, acfg)

    def test_async_algorithms_registry(self):
        assert set(ASYNC_ALGORITHMS) == {
            "fedavg", "fedprox", "fedadam", "scaffold"}
