"""Process-lifecycle unit tests (fast lane): PreemptionHandler,
StallWatchdog, the SPMD stop-flag plumbing, the ElasticRunner restart
harness, checkpoint lifecycle hardening (atexit fallback, idempotent
close), and the zero-overhead guarantee (byte-identical traced round
programs with the watchdog armed).

The end-to-end drills live in the slow lane: test_kill_drill.py
(SIGTERM → drain → exit 75 → relaunch → bitwise trajectory match) and
test_watchdog_drill.py (wedged pod → exit 75 with stacks).
"""
import json
import os
import signal
import threading
import time

import jax
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    CheckpointConfig, DataConfig, ExperimentConfig, FaultConfig,
    FederatedConfig, ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.robustness import (
    RESTART_EXIT_CODE, ElasticRunner, PreemptionHandler, StallWatchdog,
    read_checkpoint_round,
)
from fedtorch_tpu.robustness.watchdog import format_thread_stacks


class ListLogger:
    def __init__(self):
        self.lines = []

    def log(self, msg, display=None):
        self.lines.append(msg)

    def text(self):
        return "\n".join(self.lines)


def make_trainer(fault_kw=None, num_clients=6):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=10,
                        batch_size=8),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients, num_comms=4,
            online_client_rate=0.5, algorithm="fedavg",
            sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.1, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        fault=FaultConfig(**(fault_kw or {})),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    return cfg, FederatedTrainer(cfg, model, make_algorithm(cfg),
                                 data.train)


# -- PreemptionHandler -------------------------------------------------------
class TestPreemptionHandler:
    def test_sigterm_sets_flag_and_reason(self):
        with PreemptionHandler() as h:
            assert not h.stop_requested
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.stop_requested
            assert h.reason == "SIGTERM"

    def test_sigusr1_is_a_stop_signal(self):
        with PreemptionHandler() as h:
            os.kill(os.getpid(), signal.SIGUSR1)
            assert h.stop_requested
            assert h.reason == "SIGUSR1"

    def test_restore_reinstates_previous_handlers(self):
        before = signal.getsignal(signal.SIGTERM)
        h = PreemptionHandler()
        assert h.install()
        assert signal.getsignal(signal.SIGTERM) is not before
        h.restore()
        assert signal.getsignal(signal.SIGTERM) is before
        assert not h.installed

    def test_request_stop_without_signals(self):
        h = PreemptionHandler()  # never installed
        h.request_stop("watchdog")
        assert h.stop_requested
        assert h.reason == "watchdog"

    def test_second_sigint_raises_keyboard_interrupt(self):
        with PreemptionHandler() as h:
            os.kill(os.getpid(), signal.SIGINT)
            assert h.stop_requested  # first: flag only
            with pytest.raises(KeyboardInterrupt):
                os.kill(os.getpid(), signal.SIGINT)
                # signal delivery is synchronous for self-kill on the
                # main thread, but give the handler a bytecode boundary
                time.sleep(0.01)

    def test_single_sigint_after_sigterm_keeps_draining(self):
        """A SIGTERM-initiated drain must survive ONE stray Ctrl-C —
        only a repeated SIGINT escalates to KeyboardInterrupt."""
        with PreemptionHandler() as h:
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.stop_requested
            os.kill(os.getpid(), signal.SIGINT)  # must NOT raise
            time.sleep(0.01)
            assert h.stop_requested
            with pytest.raises(KeyboardInterrupt):
                os.kill(os.getpid(), signal.SIGINT)
                time.sleep(0.01)

    def test_install_off_main_thread_degrades(self):
        log = ListLogger()
        result = {}

        def worker():
            h = PreemptionHandler(logger=log)
            result["installed"] = h.install()
            h.request_stop("manual")
            result["stop"] = h.stop_requested

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert result["installed"] is False
        assert result["stop"] is True
        assert any("not on the main thread" in ln for ln in log.lines)


# -- StallWatchdog -----------------------------------------------------------
class TestStallWatchdog:
    def test_disabled_at_zero_timeout(self):
        wd = StallWatchdog(0.0)
        assert not wd.enabled
        wd.start()
        assert wd._thread is None  # no monitor thread at all
        wd.stop()

    def test_fires_after_timeout_with_stacks(self):
        log = ListLogger()
        fired = []
        wd = StallWatchdog(0.2, logger=log, exit_fn=fired.append,
                           poll_s=0.05)
        wd.start()
        wd.heartbeat(0)
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.05)
        wd.stop()
        assert fired == [RESTART_EXIT_CODE]
        text = log.text()
        assert "no round completed in" in text
        assert "last completed round: 0" in text
        assert "--- Thread MainThread" in text
        assert "runtime" in text

    def test_heartbeat_defers_firing(self):
        fired = []
        wd = StallWatchdog(0.3, logger=ListLogger(),
                           exit_fn=fired.append, poll_s=0.05)
        wd.start()
        for _ in range(10):
            wd.heartbeat()
            time.sleep(0.05)  # keeps beating well inside the timeout
        assert not fired
        wd.stop()
        assert not fired

    def test_format_thread_stacks_lists_this_thread(self):
        text = format_thread_stacks()
        assert "MainThread" in text
        assert "format_thread_stacks" in text or "test_format" in text


# -- SPMD stop-flag plumbing -------------------------------------------------
class TestStopFlagPlumbing:
    def test_scalars_carry_stop_only_when_attached(self):
        _, trainer = make_trainer()
        server, clients = trainer.init_state(jax.random.key(0))
        server, clients, metrics = trainer.run_round(server, clients)
        sc = trainer.round_host_scalars(clients, metrics)
        assert "stop" not in sc

        flag = {"stop": False}
        trainer.attach_stop_signal(lambda: flag["stop"])
        sc = trainer.round_host_scalars(clients, metrics)
        assert sc["stop"] == 0.0
        flag["stop"] = True
        sc = trainer.round_host_scalars(clients, metrics)
        assert sc["stop"] == 1.0

    def test_stop_flag_dev_single_process(self):
        _, trainer = make_trainer()
        assert float(jax.device_get(
            trainer.stop_flag_dev(False))) == 0.0
        assert float(jax.device_get(
            trainer.stop_flag_dev(True))) == 1.0


# -- zero overhead when off --------------------------------------------------
class TestTracedProgramIdentity:
    def test_watchdog_knob_leaves_round_program_byte_identical(self):
        """watchdog_timeout_s is host-only: the traced round program
        must be BYTE-identical with the watchdog armed vs off (the
        'zero overhead' acceptance bar; the runtime half is the PR 2
        recompilation sentinel in test_trace_sentinel.py)."""
        texts = []
        for kw in ({}, {"watchdog_timeout_s": 30.0}):
            _, trainer = make_trainer(fault_kw=kw)
            server, clients = trainer.init_state(jax.random.key(0))
            lowered = trainer._round_jit.lower(
                server, clients, trainer.data, trainer.val_data)
            texts.append(lowered.as_text())
        assert texts[0] == texts[1]


# -- ElasticRunner -----------------------------------------------------------
class FakeChild:
    def __init__(self, rc, on_wait=None):
        self.rc = rc
        self.pid = 4242
        self.on_wait = on_wait

    def wait(self):
        if self.on_wait is not None:
            self.on_wait()
        return self.rc

    def poll(self):
        return self.rc


def write_fake_checkpoint(ckpt_dir, round_idx):
    os.makedirs(ckpt_dir, exist_ok=True)
    with open(os.path.join(ckpt_dir, "checkpoint.ckpt"), "wb") as f:
        f.write(b"x")
    with open(os.path.join(ckpt_dir, "checkpoint.json"), "w") as f:
        json.dump({"round": round_idx}, f)


class TestElasticRunner:
    def _runner(self, ckpt_dir, script, **kw):
        """``script`` = list of (rc, round_written_during_run) pairs;
        round None = the child made no checkpoint progress."""
        cmds, delays = [], []
        it = iter(script)

        def popen(cmd, **_):
            cmds.append(cmd)
            rc, round_idx = next(it)
            on_wait = (lambda ri=round_idx: write_fake_checkpoint(
                ckpt_dir, ri)) if round_idx is not None else None
            return FakeChild(rc, on_wait)

        runner = ElasticRunner(
            ["train", "--x"], ckpt_dir=ckpt_dir, popen=popen,
            sleep_fn=delays.append, log_fn=lambda m: None, **kw)
        return runner, cmds, delays

    def test_restarts_on_75_and_appends_resume(self, tmp_path):
        ckpt = str(tmp_path)
        runner, cmds, _ = self._runner(
            ckpt, [(RESTART_EXIT_CODE, 3), (0, 6)])
        assert runner.run() == 0
        assert runner.launches == 2
        assert cmds[0] == ["train", "--x"]  # no checkpoint yet
        assert cmds[1] == ["train", "--x", "--resume", ckpt]

    def test_resume_flag_never_duplicated(self, tmp_path):
        ckpt = str(tmp_path)
        write_fake_checkpoint(ckpt, 1)
        cmds = []

        def popen(cmd, **_):
            cmds.append(cmd)
            return FakeChild(0)

        runner = ElasticRunner(["train", "--resume", "elsewhere"],
                               ckpt_dir=ckpt, popen=popen,
                               log_fn=lambda m: None)
        assert runner.run() == 0
        assert cmds[0].count("--resume") == 1  # the operator's pin wins

    def test_resume_equals_form_also_pins(self, tmp_path):
        """'--resume=<path>' must count as pinned too — appending a
        second --resume would silently override the operator's
        warm-start source (argparse last-wins)."""
        ckpt = str(tmp_path)
        write_fake_checkpoint(ckpt, 1)
        cmds = []

        def popen(cmd, **_):
            cmds.append(cmd)
            return FakeChild(0)

        runner = ElasticRunner(["train", "--resume=/warmstart"],
                               ckpt_dir=ckpt, popen=popen,
                               log_fn=lambda m: None)
        assert runner.run() == 0
        assert cmds[0] == ["train", "--resume=/warmstart"]

    def test_non_restartable_exit_propagates(self, tmp_path):
        runner, cmds, _ = self._runner(str(tmp_path), [(1, None)])
        assert runner.run() == 1
        assert runner.launches == 1

    def test_crash_loop_without_progress_gives_up(self, tmp_path):
        script = [(RESTART_EXIT_CODE, None)] * 10
        runner, cmds, delays = self._runner(str(tmp_path), script,
                                            max_restarts=2)
        assert runner.run() == RESTART_EXIT_CODE
        # initial launch + 2 budgeted restarts, then give-up
        assert runner.launches == 3
        assert runner.stalled_restarts == 3

    def test_progress_resets_the_budget(self, tmp_path):
        # every restart advances the round: 75s forever would be fine,
        # and max_restarts=1 must NOT kill a genuinely healing job
        script = [(RESTART_EXIT_CODE, r) for r in (1, 2, 3)] + [(0, 4)]
        runner, cmds, _ = self._runner(str(tmp_path), script,
                                       max_restarts=1)
        assert runner.run() == 0
        assert runner.launches == 4
        assert runner.stalled_restarts == 0

    def test_backoff_doubles_and_caps(self, tmp_path):
        script = [(RESTART_EXIT_CODE, None)] * 4 + [(0, None)]
        runner, cmds, delays = self._runner(
            str(tmp_path), script, max_restarts=10,
            backoff_base_s=1.0, backoff_max_s=4.0)
        assert runner.run() == 0
        assert delays == [1.0, 2.0, 4.0, 4.0]

    def test_read_checkpoint_round(self, tmp_path):
        assert read_checkpoint_round(None) is None
        assert read_checkpoint_round(str(tmp_path)) is None  # missing
        write_fake_checkpoint(str(tmp_path), 7)
        assert read_checkpoint_round(str(tmp_path)) == 7
        with open(os.path.join(str(tmp_path), "checkpoint.json"),
                  "w") as f:
            f.write("{corrupt")
        assert read_checkpoint_round(str(tmp_path)) is None

    def test_cli_requires_command(self, capsys):
        from fedtorch_tpu.robustness.harness import main
        assert main([]) == 2
        assert main(["--ckpt_dir", "/tmp", "--"]) == 2


# -- config / CLI surface ----------------------------------------------------
class TestLifecycleConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="watchdog_timeout_s"):
            ExperimentConfig(
                fault=FaultConfig(watchdog_timeout_s=-1.0)).finalize()
        with pytest.raises(ValueError, match="keep_last_n"):
            ExperimentConfig(
                checkpoint=CheckpointConfig(keep_last_n=-1)).finalize()

    def test_cli_flags_map(self):
        from fedtorch_tpu.cli import args_to_config, build_parser
        args = build_parser().parse_args([
            "--federated", "true", "-d", "synthetic",
            "--watchdog_timeout_s", "120",
            "--run_dir", "/runs/exp1",
            "--checkpoint_keep_last_n", "3"])
        cfg = args_to_config(args)
        assert cfg.fault.watchdog_timeout_s == 120.0
        assert cfg.checkpoint.run_dir == "/runs/exp1"
        assert cfg.checkpoint.keep_last_n == 3

    def test_supervise_subcommand_routes_to_harness(self, capsys):
        from fedtorch_tpu.cli import main
        assert main(["supervise"]) == 2  # harness usage error, not
        #                                  the training arg parser


# -- run_experiment lifecycle ------------------------------------------------
def _cli_cfg(run_dir, rounds=3, async_save=False, extra=()):
    from fedtorch_tpu.cli import args_to_config, build_parser
    argv = [
        "--federated", "true", "-d", "synthetic", "-a",
        "logistic_regression", "--num_comms", str(rounds),
        "--num_workers", "6", "--online_client_rate", "0.5",
        "--federated_sync_type", "local_step", "--local_step", "2",
        "--batch_size", "8", "--lr", "0.1", "--eval_freq", "1",
        "--debug", "false", "--run_dir", run_dir]
    if async_save:
        argv.append("--async_checkpoint")
    argv.extend(extra)
    return args_to_config(build_parser().parse_args(argv))


class TestRunExperimentLifecycle:
    def test_stop_request_drains_at_round_boundary(self, tmp_path):
        from fedtorch_tpu.cli import run_experiment
        run_dir = str(tmp_path / "run")
        cfg = _cli_cfg(run_dir, rounds=5)
        seen = []

        def cb(r, trainer, server, clients, metrics):
            seen.append(r)
            if r == 1:
                os.kill(os.getpid(), signal.SIGTERM)

        before = signal.getsignal(signal.SIGTERM)
        res = run_experiment(cfg, round_callback=cb)
        # signal lands during round 1's callback; the NEXT boundary's
        # scalar fetch observes it → drain after round 2
        assert res["preempted"] and res["preempted_at_round"] == 2
        assert seen == [0, 1, 2]
        assert read_checkpoint_round(run_dir) == 3
        # the loop's finally restored the pre-run handler — library
        # callers must not inherit a swallowing SIGTERM handler
        assert signal.getsignal(signal.SIGTERM) is before

    def test_stream_drain_leaves_resumable_checkpoint(self, tmp_path):
        """Streaming data plane × preemption: the SIGTERM lands while
        round-ahead prefetches are in flight BY CONSTRUCTION (the
        producer runs up to 2 rounds ahead of the loop). The drain
        must still write a final checkpoint, stop the feed-producer
        thread, and the resumed run must continue the exact streamed
        trajectory (bitwise vs an uninterrupted run)."""
        from fedtorch_tpu.cli import run_experiment
        run_dir = str(tmp_path / "run")
        stream = ("--data_plane", "stream")
        cfg = _cli_cfg(run_dir, rounds=6, extra=stream)

        def cb(r, trainer, server, clients, metrics):
            if r == 1:
                # prefetch pipeline is live right now
                assert any(t.name == "stream-feed-producer"
                           and t.is_alive()
                           for t in threading.enumerate())
                os.kill(os.getpid(), signal.SIGTERM)

        res = run_experiment(cfg, round_callback=cb)
        assert res["preempted"] and res["preempted_at_round"] == 2
        assert read_checkpoint_round(run_dir) == 3
        # the drain stopped the producer (no thread left blocked on
        # the feed queue across the exit-75 boundary)
        assert not any(t.name == "stream-feed-producer" and t.is_alive()
                       for t in threading.enumerate())

        # relaunch-with---resume leg: rounds 3..5 complete
        res2 = run_experiment(
            _cli_cfg(run_dir, rounds=6,
                     extra=stream + ("--resume", run_dir)))
        assert "preempted" not in res2
        assert read_checkpoint_round(run_dir) == 6

        # stitched trajectory == uninterrupted streamed run, bitwise
        ref_dir = str(tmp_path / "ref")
        run_experiment(_cli_cfg(ref_dir, rounds=6, extra=stream))
        from fedtorch_tpu.algorithms import make_algorithm
        from fedtorch_tpu.data import build_federated_data
        from fedtorch_tpu.models import define_model
        from fedtorch_tpu.parallel import FederatedTrainer
        from fedtorch_tpu.utils import maybe_resume

        def final_server(d):
            data = build_federated_data(cfg)
            model = define_model(cfg, batch_size=cfg.data.batch_size)
            tr = FederatedTrainer(cfg, model, make_algorithm(cfg),
                                  data.train)
            server, clients = tr.init_state(
                jax.random.key(cfg.train.manual_seed))
            server, _, _, resumed = maybe_resume(d, server, clients,
                                                 cfg)
            assert resumed
            return server

        a, b = final_server(run_dir), final_server(ref_dir)
        assert int(jax.device_get(a.round)) == 6
        for la, lb in zip(jax.tree.leaves(a.params),
                          jax.tree.leaves(b.params)):
            import numpy as np
            np.testing.assert_array_equal(np.asarray(la),
                                          np.asarray(lb))

    def test_async_drain_leaves_resumable_checkpoint(self, tmp_path):
        """Async commit plane × preemption (ISSUE 6 kill-drill
        satellite): SIGTERM lands mid-commit-loop under a straggler-
        heavy schedule; the drain must checkpoint at a commit
        boundary (partial buffers are never persisted — no update is
        materialized before its commit), and the resumed run must
        continue the exact commit sequence: the stitched trajectory
        equals an uninterrupted async run bitwise (the scheduler
        fast-forwards its event simulation to the checkpointed
        commit)."""
        from fedtorch_tpu.cli import run_experiment
        run_dir = str(tmp_path / "run")
        async_mode = ("--sync_mode", "async",
                      "--fault_straggler_rate", "0.4",
                      "--fault_straggler_step_frac", "0.1")
        cfg = _cli_cfg(run_dir, rounds=6, extra=async_mode)

        def cb(r, trainer, server, clients, metrics):
            if r == 1:
                os.kill(os.getpid(), signal.SIGTERM)

        res = run_experiment(cfg, round_callback=cb)
        assert res["preempted"] and res["preempted_at_round"] == 2
        assert read_checkpoint_round(run_dir) == 3
        # satellite (ISSUE 14): the staleness histogram must survive
        # the drain — a snapshot lands on the drain path AND the
        # run-end emission (which reads it before the stream teardown;
        # it used to be lost to invalidate_stream ordering). Commits
        # 0..2 each folded buffer_size updates, so the counts sum to
        # commits x m.
        from fedtorch_tpu.telemetry.schema import iter_jsonl
        hist_evs = [e for e in iter_jsonl(
            os.path.join(run_dir, "events.jsonl"))
            if e.get("event") == "async.staleness_hist"]
        assert {e["snapshot"] for e in hist_evs} >= {"drain", "final"}
        for e in hist_evs:
            assert sum(e["hist"].values()) == 3 * 1  # 3 commits x m=1

        res2 = run_experiment(
            _cli_cfg(run_dir, rounds=6,
                     extra=async_mode + ("--resume", run_dir)))
        assert "preempted" not in res2
        assert read_checkpoint_round(run_dir) == 6

        # stitched == uninterrupted, bitwise
        ref_dir = str(tmp_path / "ref")
        run_experiment(_cli_cfg(ref_dir, rounds=6, extra=async_mode))
        from fedtorch_tpu.algorithms import make_algorithm
        from fedtorch_tpu.async_plane import AsyncFederatedTrainer
        from fedtorch_tpu.data import build_federated_data
        from fedtorch_tpu.models import define_model
        from fedtorch_tpu.utils import maybe_resume

        def final_server(d):
            data = build_federated_data(cfg)
            model = define_model(cfg, batch_size=cfg.data.batch_size)
            tr = AsyncFederatedTrainer(cfg, model, make_algorithm(cfg),
                                       data.train)
            server, clients = tr.init_state(
                jax.random.key(cfg.train.manual_seed))
            server, _, _, resumed = maybe_resume(d, server, clients,
                                                 cfg)
            assert resumed
            return server

        a, b = final_server(run_dir), final_server(ref_dir)
        assert int(jax.device_get(a.round)) == 6
        import numpy as np
        for la, lb in zip(jax.tree.leaves(a.params),
                          jax.tree.leaves(b.params)):
            np.testing.assert_array_equal(np.asarray(la),
                                          np.asarray(lb))

    def test_raising_round_loop_lands_pending_async_checkpoint(
            self, tmp_path, monkeypatch):
        """Satellite regression: an exception mid-run must not drop a
        queued async checkpoint — the finally/atexit drain lands it."""
        from fedtorch_tpu.cli import run_experiment
        from fedtorch_tpu.utils import checkpoint as ckpt_mod
        run_dir = str(tmp_path / "run")
        cfg = _cli_cfg(run_dir, rounds=5, async_save=True)

        # slow the writes so round 0's checkpoint is still in flight
        # when round 1 raises
        orig_write = ckpt_mod._write_checkpoint

        def slow_write(*a, **kw):
            time.sleep(0.3)
            return orig_write(*a, **kw)

        monkeypatch.setattr(ckpt_mod, "_write_checkpoint", slow_write)

        def boom(r, trainer, server, clients, metrics):
            if r == 1:
                raise RuntimeError("round loop died")

        with pytest.raises(RuntimeError, match="round loop died"):
            run_experiment(cfg, round_callback=boom)
        # the queued round-0/1 checkpoint still hit the disk, intact
        assert read_checkpoint_round(run_dir) is not None
        with open(os.path.join(run_dir, "checkpoint.ckpt"), "rb") as f:
            blob = f.read()
        payload, why = ckpt_mod._unframe_payload(blob)
        assert why is None and payload


class TestAsyncCheckpointerLifecycle:
    def test_close_is_idempotent(self):
        from fedtorch_tpu.utils import AsyncCheckpointer
        ck = AsyncCheckpointer()
        ck.close()
        ck.close()  # second close must not deadlock on the dead worker
        assert not ck._thread.is_alive() if ck._thread else True

    def test_atexit_fallback_registered_and_unregistered(self):
        import atexit
        from fedtorch_tpu.utils import AsyncCheckpointer
        ck = AsyncCheckpointer()
        # unregister succeeds only if register happened; after close()
        # the hook must be gone (re-registering a closed checkpointer
        # at interpreter exit would be a silent no-op anyway, but the
        # hook keeps the object alive — close() must drop it)
        ck.close()
        # idempotent close already unregistered; atexit.unregister on
        # a non-registered callable is a no-op — this must not raise
        atexit.unregister(ck._atexit_close)

    def test_atexit_close_swallows_errors(self, capsys, monkeypatch):
        from fedtorch_tpu.utils import AsyncCheckpointer
        ck = AsyncCheckpointer()
        monkeypatch.setattr(
            ck, "wait",
            lambda: (_ for _ in ()).throw(RuntimeError("disk full")))
        ck._atexit_close()  # must not raise at interpreter exit
        assert ck._closed
        assert "atexit flush failed" in capsys.readouterr().err
