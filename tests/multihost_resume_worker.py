"""Worker for the 4-process mid-run checkpoint-restore test
(test_multihost_resume.py — VERDICT r3 #8).

Each process owns 2 virtual CPU devices; 4 processes form an 8-device
global mesh. Three modes replay the same seeded experiment
(bring-up shared with the 2-process smoke via mh_common.py):

  full     — 4 uninterrupted rounds; print every round's fingerprint
  first    — rounds 1-2, collective checkpoint, exit (the "crash")
  resume   — fresh processes restore the cross-host checkpoint and run
             rounds 3-4; print those rounds' fingerprints
  degraded — 2 processes (a 4-device mesh: the "surviving slice" after
             losing half the pod) restore the SAME 8-device-mesh
             checkpoint and run rounds 3-4

``full``'s rounds 3-4 and ``resume``'s / ``degraded``'s rounds 3-4
must print IDENTICAL per-round fingerprints: the checkpoint carries
full round state (server+client params, aux, counters, PRNG) for the
REAL clients only — the mesh-dependent padding tail is stripped on
save and re-grafted on restore — so an interrupted run is
bit-indistinguishable from an uninterrupted one round by round, across
a simulated DCN boundary AND across a mesh-shape change (the
degraded-pod resume contract, docs/multihost.md "Failure model").
Run as:

    python tests/multihost_resume_worker.py <port> <pid> <mode> <ckpt_dir>
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from mh_common import bringup, configure_env, round_fingerprint  # noqa: E402

port, pid, mode, ckpt_dir = (sys.argv[1], int(sys.argv[2]),
                             sys.argv[3], sys.argv[4])
configure_env(local_devices=2)  # before the first jax import

n_procs = 2 if mode == "degraded" else 4
jax, cfg, trainer = bringup(port, pid, num_processes=n_procs,
                            local_devices=2, online_client_rate=0.5)
from fedtorch_tpu.utils import maybe_resume, save_checkpoint  # noqa: E402

server, clients = trainer.init_state(jax.random.key(0))

if mode in ("resume", "degraded"):
    server, clients, best, resumed = maybe_resume(
        ckpt_dir, server, clients, cfg, None)
    assert resumed and int(server.round) == 2, (resumed, server.round)
    first_round, rounds = 3, 2      # rounds 3-4
elif mode == "first":
    first_round, rounds = 1, 2      # rounds 1-2
elif mode == "full":
    first_round, rounds = 1, 4
else:
    raise SystemExit(f"unknown mode {mode}")

for i in range(rounds):
    server, clients, metrics = trainer.run_round(server, clients)
    jax.block_until_ready(server.params)
    if mode != "first":
        fp = round_fingerprint(jax, trainer, server, clients, metrics)
        print(f"TRAJ pid={pid} round={first_round + i} {fp}",
              flush=True)

if mode == "first":
    from jax.experimental import multihost_utils
    save_checkpoint(ckpt_dir, server, clients, cfg, best_prec1=0.5,
                    is_best=False)
    multihost_utils.sync_global_devices("ckpt-written")
    if pid == 0:
        assert os.path.exists(os.path.join(ckpt_dir, "checkpoint.ckpt"))
    print(f"CKPT_SAVED pid={pid}", flush=True)
jax.distributed.shutdown()
