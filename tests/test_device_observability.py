"""Device-side observability (docs/observability.md "Device-side",
ISSUE 8): compiled-program cost capture, profiler-trace attribution,
and the per-round measured-MFU / HBM gauges.

The contracts made executable here:

* ``program_costs.json`` is schema-versioned and validated like the
  metrics row (uncataloged fields rejected, graceful ``None`` for
  backend-silent statistics);
* cost capture is HOST-ONLY: the uninstrumented twins lower to HLO
  byte-identical to the live round/commit programs, and with capture +
  MFU gauges enabled the programs still trace exactly once — across
  device/stream planes x sync/async modes;
* the trace attributor buckets >= 95% of device time into named
  categories on the checked-in fixture AND on a real CPU-backend
  capture, handles malformed/empty traces, and renders through
  ``fedtorch-tpu report --device``.
"""
import gzip
import json
import os

import jax
import numpy as np
import pytest

from fedtorch_tpu.telemetry import validate_metrics_row
from fedtorch_tpu.telemetry.costs import (
    FLOPS_XLA, PROGRAM_COSTS_SCHEMA, ProgramCostCapture, cost_summary,
    lowered_cost, program_flops, read_program_costs,
    resolve_peak_tflops, train_step_flops, validate_program_costs,
)
from fedtorch_tpu.tools import trace_attrib
from fedtorch_tpu.utils.tracing import RecompilationSentinel
from test_telemetry import make_trainer

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "data",
                           "device_attrib")

PLANES = [("device", "sync"), ("stream", "sync"),
          ("device", "async"), ("stream", "async")]

TRACE_NAMES = {
    ("device", "sync"): "trace_name",
    ("stream", "sync"): "stream_trace_name",
    ("device", "async"): "commit_trace_name",
    ("stream", "async"): "commit_stream_trace_name",
}


def capture_for(trainer, tmp_path, **kw):
    cap = ProgramCostCapture(
        str(tmp_path), compute_dtype="float32",
        arch="logistic_regression", batch_size=8,
        local_steps=trainer.local_steps, k_online=trainer.k_online,
        num_devices=int(trainer.mesh.devices.size), backend="cpu",
        **kw)
    return cap


# -- program_costs.json schema ----------------------------------------------
class TestProgramCostsSchema:
    def test_capture_roundtrip_validates(self, tmp_path):
        trainer = make_trainer()
        server, clients = trainer.init_state(jax.random.key(0))
        programs, primary = trainer.lowered_cost_programs(
            server, clients, num_scan_rounds=2)
        assert primary == "round"
        assert set(programs) == {"round", "rounds_scan[2]"}
        cap = capture_for(trainer, tmp_path)
        doc = cap.capture(programs, primary=primary)
        assert doc is not None and cap.captured
        got = read_program_costs(str(tmp_path))
        assert got["schema"] == PROGRAM_COSTS_SCHEMA
        assert got["primary"] == "round"
        # the CPU backend reports real costs: flops positive, the scan
        # of 2 rounds costs more than one round
        r = got["programs"]["round"]
        assert r["flops"] > 0 and r["flops_source"] == FLOPS_XLA
        assert r["peak_hbm_bytes"] > 0 and r["bytes_accessed"] > 0
        assert got["programs"]["rounds_scan[2]"]["flops"] > r["flops"]

    def test_uncataloged_program_field_rejected(self, tmp_path):
        trainer = make_trainer()
        server, clients = trainer.init_state(jax.random.key(0))
        programs, primary = trainer.lowered_cost_programs(server,
                                                          clients)
        doc = capture_for(trainer, tmp_path).capture(programs,
                                                     primary=primary)
        doc["programs"]["round"]["my_new_stat"] = 1.0
        with pytest.raises(ValueError, match="uncataloged"):
            validate_program_costs(doc)
        del doc["programs"]["round"]["my_new_stat"]
        doc["surprise"] = True
        with pytest.raises(ValueError, match="uncataloged"):
            validate_program_costs(doc)

    def test_missing_required_and_schema_skew_rejected(self):
        with pytest.raises(ValueError, match="schema"):
            validate_program_costs({"schema": "fedtorch_tpu/v999"})
        doc = {"schema": PROGRAM_COSTS_SCHEMA, "created_unix": 0.0,
               "backend": "cpu", "num_devices": 1,
               "compute_dtype": "float32",
               "peak_tflops_per_chip": 98.0, "peak_source": "x",
               "programs": {"round": {"flops": 1.0}}}
        validate_program_costs(doc)
        del doc["peak_source"]
        with pytest.raises(ValueError, match="peak_source"):
            validate_program_costs(doc)
        doc["peak_source"] = "x"
        doc["programs"] = {}
        with pytest.raises(ValueError, match="non-empty"):
            validate_program_costs(doc)

    def test_graceful_none_on_dead_backend(self):
        # a Lowered whose compile explodes must yield the all-None
        # summary (+ error note) — and still validate
        class Dead:
            def compile(self):
                raise RuntimeError("backend gone")

        rec = lowered_cost(Dead())
        assert rec["flops"] is None and rec["flops_source"] is None
        assert "backend gone" in rec["error"]
        validate_program_costs({
            "schema": PROGRAM_COSTS_SCHEMA, "created_unix": 0.0,
            "backend": None, "num_devices": 1,
            "compute_dtype": "float32", "peak_tflops_per_chip": 98.0,
            "peak_source": "x", "programs": {"round": rec}})
        assert cost_summary(None)["flops"] is None

    def test_peak_resolution(self, monkeypatch):
        monkeypatch.delenv("BENCH_PEAK_TFLOPS", raising=False)
        assert resolve_peak_tflops("bfloat16") == (
            197.0, "default:tpu_v5e:bfloat16")
        assert resolve_peak_tflops("float32")[0] == 98.0
        monkeypatch.setenv("BENCH_PEAK_TFLOPS", "123.5")
        assert resolve_peak_tflops("float32") == (
            123.5, "env:BENCH_PEAK_TFLOPS")

    def test_shared_flops_probes(self):
        # the dedup target: the generic jit probe and the train-step
        # probe both report positive FLOPs on the CPU backend
        assert program_flops(lambda x: (x @ x).sum(),
                             np.ones((16, 16), np.float32)) > 0
        trainer = make_trainer()
        assert train_step_flops(trainer.model, 8) > 0


# -- host-only: trace-once + byte-identical HLO -----------------------------
class TestCostCaptureHostOnly:
    @pytest.mark.parametrize("plane,sync_mode", PLANES)
    def test_capture_mid_loop_traces_once(self, plane, sync_mode,
                                          tmp_path):
        trainer = make_trainer(plane=plane, sync_mode=sync_mode)
        server, clients = trainer.init_state(jax.random.key(0))
        cap = capture_for(trainer, tmp_path)
        with RecompilationSentinel() as s:
            server, clients, m = trainer.run_round(server, clients)
            programs, primary = trainer.lowered_cost_programs(server,
                                                              clients)
            cap.capture(programs, primary=primary)
            server, clients, m = trainer.run_round(server, clients)
        trainer.invalidate_stream()
        s.assert_traces(getattr(trainer, TRACE_NAMES[(plane,
                                                      sync_mode)]),
                        expected=1)
        doc = read_program_costs(str(tmp_path))
        assert doc["primary"] == primary
        assert doc["programs"][primary]["flops"] > 0
        gauges = cap.round_gauges(0.5)
        assert gauges["model_flops_utilization"] > 0
        assert gauges["hbm_program_peak_bytes"] > 0
        assert gauges["hbm_live_bytes"] > 0
        validate_metrics_row(dict(
            {"round": 0, "round_s": 0.5, "loss": 1.0, "acc": 0.5,
             "lr": 0.1, "n_online": 4.0, "comm_bytes": 1e6}, **gauges))

    def test_twin_hlo_byte_identical_device_sync(self):
        trainer = make_trainer()
        server, clients = trainer.init_state(jax.random.key(0))
        live = trainer._round_jit.lower(
            server, clients, trainer.data, trainer.val_data).as_text()
        twin = trainer.lowered_cost_programs(server, clients)[0][
            "round"].as_text()
        assert live == twin

    def test_twin_hlo_byte_identical_stream(self):
        trainer = make_trainer(plane="stream")
        server, clients = trainer.init_state(jax.random.key(0))
        feed = trainer._next_stream_feed(server)
        live = trainer._round_stream_jit.lower(server, clients,
                                               feed).as_text()
        twin = trainer.lowered_cost_programs(server, clients)[0][
            "round_stream"].as_text()
        trainer.invalidate_stream()
        assert live == twin

    def test_twin_hlo_byte_identical_async_commit(self):
        from fedtorch_tpu.async_plane.commit import CommitJobs
        trainer = make_trainer(sync_mode="async")
        server, clients = trainer.init_state(jax.random.key(0))
        trainer._ensure_schedule(server)
        plan = trainer._sched.next_commit()
        jobs = CommitJobs(idx=plan.idx, version=plan.version,
                          dispatch=plan.dispatch,
                          straggler=plan.straggler)
        live = trainer._commit_jit.lower(server, clients, jobs,
                                         trainer.data).as_text()
        twin = trainer.lowered_cost_programs(server, clients)[0][
            "commit"].as_text()
        trainer.invalidate_stream()
        assert live == twin

    def test_mfu_gauge_definition(self, tmp_path):
        # model_flops_utilization == flops / (round_s * peak * chips)
        trainer = make_trainer()
        server, clients = trainer.init_state(jax.random.key(0))
        programs, primary = trainer.lowered_cost_programs(server,
                                                          clients)
        cap = capture_for(trainer, tmp_path)
        doc = cap.capture(programs, primary=primary)
        flops = doc["programs"]["round"]["flops"]
        n_dev = int(trainer.mesh.devices.size)
        got = cap.round_gauges(0.25)["model_flops_utilization"]
        assert got == pytest.approx(
            flops / (0.25 * 98.0 * 1e12 * n_dev))
        # gauges are empty before a successful capture
        assert capture_for(trainer, tmp_path).round_gauges(0.25) == {}

    def test_resume_adopts_existing_capture(self, tmp_path):
        # elastic restarts reuse the run dir: a second capture object
        # adopts the recorded document instead of recompiling (resumed
        # runs bypass the persistent compile cache)
        trainer = make_trainer()
        server, clients = trainer.init_state(jax.random.key(0))
        programs, primary = trainer.lowered_cost_programs(server,
                                                          clients)
        capture_for(trainer, tmp_path).capture(programs,
                                               primary=primary)
        cap2 = capture_for(trainer, tmp_path)
        assert cap2.load_existing() and cap2.captured
        assert cap2.round_gauges(0.5)["model_flops_utilization"] > 0
        assert not capture_for(trainer,
                               tmp_path / "fresh").load_existing()
        # a valid doc WITHOUT a usable primary still adopts (gauges
        # off) — half-adopting would pay the resume recompile this
        # path exists to avoid
        doc = json.loads((tmp_path / "program_costs.json").read_text())
        del doc["primary"]
        (tmp_path / "program_costs.json").write_text(json.dumps(doc))
        cap3 = capture_for(trainer, tmp_path)
        assert cap3.load_existing() and cap3.captured
        assert cap3.round_gauges(0.5) == {}

    def test_capture_failure_absorbed(self, tmp_path):
        class Dead:
            def compile(self):
                raise RuntimeError("nope")

        logs = []
        cap = capture_for(make_trainer(), tmp_path,
                          log=lambda m: logs.append(m))
        doc = cap.capture({"round": Dead()}, primary="round")
        # per-program failure still yields a valid document with the
        # error noted; gauges stay off (no flops)
        assert doc is not None
        assert doc["programs"]["round"]["error"]
        assert "model_flops_utilization" not in cap.round_gauges(0.5)


# -- trace attribution: fixture ---------------------------------------------
class TestTraceAttribFixture:
    def test_exact_category_totals(self):
        doc = trace_attrib.attribute(FIXTURE_DIR)
        cats = doc["categories"]
        expect = {"matmul_conv_mxu": 100.0, "elementwise": 60.0,
                  "collective": 30.0, "reduce": 20.0,
                  "copy_reshape_transpose": 10.0,
                  "infeed_outfeed_h2d": 5.0, "other": 5.0,
                  "idle_gap": 10.0}
        assert {c: cats[c]["time_us"] for c in expect} == expect
        assert doc["total_us"] == 240.0
        assert doc["span_us"] == 240.0 and doc["busy_us"] == 230.0
        assert doc["device_lanes"] == 1 and doc["device_events"] == 8
        # the python-lane PjitFunction event was never selected
        assert "PjitFunction" not in {o["name"] for o in doc["top_ops"]}

    def test_attribution_invariant(self):
        doc = trace_attrib.attribute(FIXTURE_DIR)
        assert doc["attributed_frac"] == pytest.approx(1 - 5.0 / 240.0)
        assert doc["attributed_ok"]

    def test_invariant_flags_unknown_heavy_trace(self, tmp_path):
        evs = [{"ph": "X", "pid": 1, "tid": 1, "name": "mystery.1",
                "ts": 0.0, "dur": 90.0, "args": {"hlo_op": "mystery.1"}},
               {"ph": "X", "pid": 1, "tid": 1, "name": "dot.1",
                "ts": 90.0, "dur": 10.0, "args": {"hlo_op": "dot.1"}}]
        p = tmp_path / "bad.trace.json"
        p.write_text(json.dumps({"traceEvents": evs}))
        doc = trace_attrib.attribute(str(p))
        assert doc["attributed_frac"] == pytest.approx(0.1)
        assert not doc["attributed_ok"]

    def test_nested_events_self_time_split(self):
        # a wrapper spanning its children contributes only self time
        evs = [{"ph": "X", "pid": 1, "tid": 1, "name": "while.1",
                "ts": 0.0, "dur": 100.0, "args": {"hlo_op": "while.1"}},
               {"ph": "X", "pid": 1, "tid": 1, "name": "dot.1",
                "ts": 10.0, "dur": 60.0, "args": {"hlo_op": "dot.1"}},
               {"ph": "X", "pid": 1, "tid": 1, "name": "tanh.1",
                "ts": 70.0, "dur": 20.0, "args": {"hlo_op": "tanh.1"}}]
        doc = trace_attrib.attribute_events(evs)
        assert doc["cat_us"]["matmul_conv_mxu"] == 60.0
        assert doc["cat_us"]["elementwise"] == 20.0
        assert doc["cat_us"]["control_flow"] == 20.0  # while self time
        assert doc["idle_us"] == 0.0

    def test_stray_out_of_window_event_not_idle(self):
        # the profiler occasionally flushes a stray pre-window event;
        # a 1us op seconds away must not read as seconds of idle
        evs = [{"ph": "X", "pid": 1, "tid": 1, "name": "reduce.9",
                "ts": 5.0, "dur": 1.0, "args": {"hlo_op": "reduce.9"}},
               {"ph": "X", "pid": 1, "tid": 1, "name": "dot.1",
                "ts": 5e6, "dur": 400.0, "args": {"hlo_op": "dot.1"}},
               {"ph": "X", "pid": 1, "tid": 1, "name": "tanh.1",
                "ts": 5e6 + 410, "dur": 90.0,
                "args": {"hlo_op": "tanh.1"}}]
        doc = trace_attrib.attribute_events(evs)
        assert doc["idle_us"] == pytest.approx(10.0)

    def test_malformed_trace_raises(self, tmp_path):
        p = tmp_path / "broken.trace.json.gz"
        p.write_bytes(gzip.compress(b"{not json"))
        with pytest.raises(ValueError, match="broken"):
            trace_attrib.attribute(str(p))
        q = tmp_path / "noevents.trace.json"
        q.write_text(json.dumps({"foo": 1}))
        with pytest.raises(ValueError, match="traceEvents"):
            trace_attrib.attribute(str(q))

    def test_zero_duration_events_render_na(self, tmp_path, capsys):
        # events selected but no durations: render must say n/a, not
        # crash on the None attributed fraction
        evs = [{"ph": "X", "pid": 1, "tid": 1, "name": "dot.1",
                "ts": 5.0, "args": {"hlo_op": "dot.1"}}]
        p = tmp_path / "zero.trace.json"
        p.write_text(json.dumps({"traceEvents": evs}))
        doc = trace_attrib.attribute(str(p))
        assert doc["attributed_frac"] is None
        assert "n/a" in trace_attrib.render(doc)
        assert trace_attrib.main([str(p)]) == 0

    def test_empty_dir_attributes_nothing(self, tmp_path):
        doc = trace_attrib.attribute(str(tmp_path))
        assert doc["categories"] == {} and not doc["attributed_ok"]
        assert doc["attributed_frac"] is None
        assert trace_attrib.main([str(tmp_path)]) == 2

    def test_main_writes_out_and_render(self, tmp_path, capsys):
        out = tmp_path / "attrib.json"
        txt = tmp_path / "attrib.txt"
        rc = trace_attrib.main([FIXTURE_DIR, "--out", str(out),
                                "--render", str(txt)])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["schema"] == trace_attrib.TRACE_ATTRIB_SCHEMA
        assert "matmul_conv_mxu" in txt.read_text()
        assert "attributed" in capsys.readouterr().out

    @pytest.mark.parametrize("name,cat", [
        ("convolution.12", "matmul_conv_mxu"),
        ("dot.8", "matmul_conv_mxu"),
        ("reduce-window.1", "reduce"),
        ("reduce_add_fusion", "reduce"),
        ("reduce-scatter.2", "collective"),
        ("all-gather.1", "collective"),
        ("copy-start.3", "infeed_outfeed_h2d"),
        ("outfeed", "infeed_outfeed_h2d"),
        ("dynamic-update-slice.4", "copy_reshape_transpose"),
        ("transpose.9", "copy_reshape_transpose"),
        ("loop_fusion", "elementwise"),
        ("fusion.17", "elementwise"),
        ("tanh.6", "elementwise"),
        ("threefry2x32", "elementwise"),
        # dtype casts are NOT MXU work: the conv rule must not eat
        # 'convert' (a bf16 trace is full of casts)
        ("convert.3", "elementwise"),
        ("convert_fusion", "elementwise"),
        ("bitcast-convert.1", "copy_reshape_transpose"),
        # canonical long-form HLO names (jnp.exp lowers to
        # 'exponential', % to 'remainder')
        ("exponential.1", "elementwise"),
        ("exponential-minus-one", "elementwise"),
        ("remainder.2", "elementwise"),
        ("atan2.1", "elementwise"),
        ("shift-left.4", "elementwise"),
        # control-flow shells are a named line item; unknown custom
        # kernels are not
        ("while.168", "control_flow"),
        ("conditional.2", "control_flow"),
        ("call.7", "control_flow"),
        ("custom-call.2", "other"),
    ])
    def test_category_rules(self, name, cat):
        assert trace_attrib.categorize(name) == cat


# -- end-to-end: CPU capture -> attribute -> report -------------------------
class TestEndToEndCapture:
    def test_cpu_capture_attributes_and_reports(self, tmp_path,
                                                capsys):
        """The acceptance bar: a real CPU-backend capture of the round
        program attributes >= 95% of device time into named
        categories, and ``fedtorch-tpu report --device`` renders it."""
        from fedtorch_tpu.utils.tracing import capture_round_trace
        trainer = make_trainer()
        server, clients = trainer.init_state(jax.random.key(0))
        server, clients, _ = trainer.run_round(server, clients)  # warm
        cap_dir = str(tmp_path / "capture")
        server, clients, _ = capture_round_trace(
            cap_dir, trainer.run_round, server, clients)
        doc = trace_attrib.attribute(cap_dir)
        assert doc["device_events"] > 0
        assert doc["attributed_frac"] >= 0.95, doc
        assert doc["categories"]["matmul_conv_mxu"]["time_us"] > 0 \
            or doc["categories"]["elementwise"]["time_us"] > 0

        # program_costs beside the trace: report --device renders both
        programs, primary = trainer.lowered_cost_programs(server,
                                                          clients)
        capture_for(trainer, tmp_path / "capture").capture(
            programs, primary=primary)
        from fedtorch_tpu.cli import main
        assert main(["report", cap_dir, "--device"]) == 0
        out = capsys.readouterr().out
        assert "device-time attribution" in out
        assert "program costs" in out
        assert "attributed:" in out

    def test_report_device_without_metrics_or_traces_errors(
            self, tmp_path):
        from fedtorch_tpu.cli import main
        assert main(["report", str(tmp_path), "--device"]) == 2

    def test_report_device_surfaces_invalid_costs_file(self, tmp_path,
                                                       capsys):
        # a corrupt program_costs.json IS a (broken) capture: the
        # validation error must be shown, not "file not found"
        (tmp_path / "program_costs.json").write_text(
            json.dumps({"schema": "fedtorch_tpu.program_costs/v999"}))
        from fedtorch_tpu.cli import main
        assert main(["report", str(tmp_path), "--device"]) == 0
        out = capsys.readouterr().out
        assert "unreadable" in out and "v999" in out


class TestCliRunDeviceGauges:
    def test_mini_run_emits_costs_and_gauges(self, tmp_path):
        """run_experiment writes program_costs.json and every metrics
        row carries the measured-MFU + HBM gauges (schema-valid)."""
        from test_telemetry import _cli_cfg

        from fedtorch_tpu.cli import run_experiment
        from fedtorch_tpu.telemetry import iter_jsonl
        run_dir = str(tmp_path / "run")
        run_experiment(_cli_cfg(run_dir, rounds=3))
        doc = read_program_costs(run_dir)
        assert doc["primary"] == "round"
        assert {"round", "eval"} <= set(doc["programs"])
        assert doc["programs"]["eval"]["flops"] > 0
        rows = [r for r in iter_jsonl(os.path.join(run_dir,
                                                   "metrics.jsonl"))
                if "schema" not in r]
        assert len(rows) == 3
        for r in rows:
            validate_metrics_row(r)
            assert r["model_flops_utilization"] > 0
            assert r["hbm_program_peak_bytes"] > 0
            assert r["hbm_live_bytes"] > 0

    def test_telemetry_off_writes_no_costs(self, tmp_path):
        from test_telemetry import _cli_cfg

        from fedtorch_tpu.cli import run_experiment
        run_dir = str(tmp_path / "run")
        run_experiment(_cli_cfg(run_dir, rounds=2,
                                extra=("--telemetry", "off")))
        assert not os.path.exists(
            os.path.join(run_dir, "program_costs.json"))
