"""Fault-tolerance tests (ISSUE 1): deterministic chaos schedules,
crash masking + weight renormalization, straggler step cuts, update
guards (NaN rejection / norm clipping), supervisor rollback semantics,
crash-safe checkpoint resume, and multihost init retry."""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FaultConfig, FederatedConfig, MeshConfig,
    ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.robustness import RoundSupervisor, draw_chaos_plan
from fedtorch_tpu.robustness.chaos import poison_tree
from fedtorch_tpu.robustness.guards import screen_payloads
from fedtorch_tpu.utils.diagnostics import model_norms


def make_trainer(fault=None, algorithm="fedavg", num_clients=8, rate=1.0,
                 lr=0.1, local_step=3, sync_type="local_step"):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20,
                        batch_size=32, synthetic_alpha=0.5,
                        synthetic_beta=0.5),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients, num_comms=20,
            online_client_rate=rate, algorithm=algorithm,
            sync_type=sync_type),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=lr, weight_decay=0.0),
        train=TrainConfig(local_step=local_step),
        fault=fault if fault is not None else FaultConfig(),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)


def all_finite(tree) -> bool:
    return all(bool(jnp.all(jnp.isfinite(x)))
               for x in jax.tree.leaves(tree))


# -- chaos schedule ---------------------------------------------------------
class TestChaosDeterminism:
    def test_same_key_same_plan(self):
        flt = FaultConfig(client_drop_rate=0.3, straggler_rate=0.3,
                          nan_inject_rate=0.2)
        a = draw_chaos_plan(jax.random.key(3), 16, flt)
        b = draw_chaos_plan(jax.random.key(3), 16, flt)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_different_keys_differ(self):
        flt = FaultConfig(client_drop_rate=0.5)
        plans = [np.asarray(draw_chaos_plan(jax.random.key(s), 64,
                                            flt).survive)
                 for s in range(4)]
        assert any(not np.array_equal(plans[0], p) for p in plans[1:])

    def test_disabled_classes_are_constant(self):
        plan = draw_chaos_plan(jax.random.key(0), 8, FaultConfig())
        np.testing.assert_array_equal(np.asarray(plan.survive), np.ones(8))
        np.testing.assert_array_equal(np.asarray(plan.budget_scale),
                                      np.ones(8))
        np.testing.assert_array_equal(np.asarray(plan.nan_inject),
                                      np.zeros(8))

    def test_round_replay_is_bit_exact(self):
        """Two trainers with the same seed replay the identical fault
        schedule AND the identical numerics."""
        flt = FaultConfig(client_drop_rate=0.3, straggler_rate=0.3,
                          nan_inject_rate=0.1, guard_updates=True)
        outs = []
        for _ in range(2):
            t = make_trainer(fault=flt)
            s, c = t.init_state(jax.random.key(5))
            for _ in range(3):
                s, c, m = t.run_round(s, c)
            outs.append((jax.tree.map(np.asarray, s.params),
                         float(m.dropped_clients),
                         float(m.rejected_updates)))
        p0, p1 = outs[0][0], outs[1][0]
        for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1)):
            np.testing.assert_array_equal(a, b)
        assert outs[0][1:] == outs[1][1:]


# -- crash masking ----------------------------------------------------------
class TestCrashInjection:
    def test_all_crash_round_is_a_noop(self):
        t = make_trainer(fault=FaultConfig(client_drop_rate=1.0))
        s, c = t.init_state(jax.random.key(0))
        p0 = jax.tree.map(np.asarray, s.params)
        c0 = jax.tree.map(np.asarray, c)
        s2, c2, m = t.run_round(s, c)
        for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(s2.params)):
            np.testing.assert_array_equal(a, np.asarray(b))
        # crashed clients roll back to round start (fail-stop)
        for a, b in zip(jax.tree.leaves(c0), jax.tree.leaves(
                jax.tree.map(np.asarray, c2))):
            np.testing.assert_array_equal(a, b)
        assert float(m.dropped_clients) == t.k_online
        assert float(m.online_mask.sum()) == 0.0
        assert float(m.comm_bytes) == 0.0

    def test_partial_crash_training_continues(self):
        """drop_rate=0.25: all rounds complete host-exception-free,
        metrics report drops, server stays finite and still learns."""
        t = make_trainer(fault=FaultConfig(client_drop_rate=0.25), lr=0.5)
        s, c = t.init_state(jax.random.key(1))
        dropped = 0.0
        first = last = None
        for r in range(12):
            s, c, m = t.run_round(s, c)
            dropped += float(m.dropped_clients)
            n = max(float(m.online_mask.sum()), 1.0)
            loss = float(m.train_loss.sum()) / n
            first = loss if first is None else first
            last = loss
        assert dropped > 0
        assert all_finite(s.params)
        assert last < first  # still converging through the chaos

    def test_survivor_weights_renormalized(self):
        """One local step, linear model: the server update must equal the
        SURVIVOR-average delta with the fault-free total weight mass —
        i.e. dropping clients must not shrink the server step toward 0."""
        flt = FaultConfig(client_drop_rate=0.45)
        t = make_trainer(fault=flt, local_step=1, lr=0.1)
        s, c = t.init_state(jax.random.key(2))
        p0 = jax.tree.map(np.asarray, s.params)
        s2, _, m = t.run_round(s, c)
        n_online = float(m.online_mask.sum())
        assert 0 < n_online < t.k_online  # the draw dropped some, not all
        # fault-free reference run from the same init (capture before
        # run_round — the round jit donates its input buffers)
        t_ref = make_trainer(local_step=1, lr=0.1)
        s_ref, c_ref = t_ref.init_state(jax.random.key(2))
        p0_ref = jax.tree.map(np.asarray, s_ref.params)
        s_ref2, _, _ = t_ref.run_round(s_ref, c_ref)
        # per-leaf: ||update_chaos|| must be the same order as the
        # fault-free update (renormalized), NOT scaled by survivors/k
        upd = np.concatenate([
            (np.asarray(b) - a).ravel()
            for a, b in zip(jax.tree.leaves(p0),
                            jax.tree.leaves(s2.params))])
        upd_ref = np.concatenate([
            (np.asarray(b) - a).ravel()
            for a, b in zip(jax.tree.leaves(p0_ref),
                            jax.tree.leaves(s_ref2.params))])
        ratio = np.linalg.norm(upd) / np.linalg.norm(upd_ref)
        assert 0.5 < ratio < 2.0  # renormalized, not survivors/k ~ 0.5-


# -- stragglers -------------------------------------------------------------
class TestStragglers:
    def test_step_budget_cut(self):
        flt = FaultConfig(straggler_rate=0.5, straggler_step_frac=0.34)
        t = make_trainer(fault=flt, local_step=3)
        s, c = t.init_state(jax.random.key(0))
        s, c, m = t.run_round(s, c)
        li = np.asarray(c.local_index)[:t.num_clients]
        # ceil(3 * 0.34) = 2 for stragglers, 3 for the rest
        assert set(li.tolist()) <= {2, 3}
        n_strag = int(np.sum(li == 2))
        assert n_strag == int(float(m.straggler_clients))
        assert n_strag > 0

    def test_straggler_partial_update_aggregates(self):
        flt = FaultConfig(straggler_rate=1.0, straggler_step_frac=0.5)
        t = make_trainer(fault=flt, local_step=4)
        s, c = t.init_state(jax.random.key(3))
        p0 = jax.tree.map(np.asarray, s.params)
        s2, c2, m = t.run_round(s, c)
        # everyone straggled at 2/4 steps, but partial updates still move
        # the server
        assert float(m.straggler_clients) == t.k_online
        assert any(np.abs(a - np.asarray(b)).max() > 0
                   for a, b in zip(jax.tree.leaves(p0),
                                   jax.tree.leaves(s2.params)))
        np.testing.assert_array_equal(
            np.asarray(c2.local_index)[:t.num_clients], 2)


# -- update guards ----------------------------------------------------------
class TestUpdateGuards:
    def _stack(self, vals):
        return {"w": jnp.asarray(vals, jnp.float32)}

    def test_nonfinite_rejected(self):
        deltas = self._stack([[1., 1.], [jnp.nan, 1.], [1., 2.]])
        flt = FaultConfig(guard_updates=True)
        payloads, rep = screen_payloads(deltas, deltas, jnp.ones(3), flt)
        np.testing.assert_array_equal(np.asarray(rep.accept), [1, 0, 1])
        assert float(rep.rejected) == 1.0
        assert all_finite(payloads)  # NaN payload zeroed by select

    def test_norm_explosion_rejected_and_clipped(self):
        deltas = self._stack([[1., 0.], [0., 1.], [1., 1.], [500., 0.]])
        flt = FaultConfig(guard_updates=True, guard_norm_multiplier=10.0)
        _, rep = screen_payloads(deltas, deltas, jnp.ones(4), flt)
        np.testing.assert_array_equal(np.asarray(rep.accept), [1, 1, 1, 0])
        # clip mode keeps it, scaled onto the threshold
        flt_clip = FaultConfig(guard_updates=True,
                               guard_norm_multiplier=10.0,
                               guard_mode="clip")
        payloads, rep2 = screen_payloads(deltas, deltas, jnp.ones(4),
                                         flt_clip)
        np.testing.assert_array_equal(np.asarray(rep2.accept), [1, 1, 1, 1])
        assert float(rep2.clipped) == 1.0
        clipped_norm = float(jnp.linalg.norm(payloads["w"][3]))
        med = float(np.median([1.0, 1.0, np.sqrt(2.0), 500.0]))
        assert clipped_norm == pytest.approx(10.0 * med, rel=1e-5)

    def test_crashed_clients_excluded_from_median(self):
        # the huge delta survives; the crashed moderate ones must not
        # drag the median up (or down) — only survivors define scale
        deltas = self._stack([[1., 0.], [0., 1.], [1., 1.], [500., 0.]])
        flt = FaultConfig(guard_updates=True, guard_norm_multiplier=10.0)
        survive = jnp.asarray([1., 1., 1., 0.])
        _, rep = screen_payloads(deltas, deltas, survive, flt)
        # client 3 crashed (not "rejected"); others accepted
        np.testing.assert_array_equal(np.asarray(rep.accept), [1, 1, 1, 0])
        assert float(rep.rejected) == 0.0

    def test_nan_delta_rejected_server_stays_finite(self):
        """End to end: a forced-NaN upload is rejected by the guard and
        the server state stays finite (the acceptance scenario)."""
        flt = FaultConfig(nan_inject_rate=0.4, guard_updates=True)
        t = make_trainer(fault=flt)
        s, c = t.init_state(jax.random.key(0))
        rejected = 0.0
        for _ in range(5):
            s, c, m = t.run_round(s, c)
            rejected += float(m.rejected_updates)
            assert all_finite(s.params)
            assert all_finite(s.opt)
        assert rejected > 0

    def test_nan_inject_keeps_delta_stateful_aux_finite(self):
        """Regression: the wire-level poison must NOT leak into
        client_post's persistent aux updates (FedGATE's tracking variate
        consumes the round delta) — a one-round wire fault must not kill
        the client forever."""
        flt = FaultConfig(nan_inject_rate=0.5, guard_updates=True)
        t = make_trainer(fault=flt, algorithm="fedgate")
        s, c = t.init_state(jax.random.key(0))
        rejected = 0.0
        for _ in range(4):
            s, c, m = t.run_round(s, c)
            rejected += float(m.rejected_updates)
            assert all_finite(s.params)
            assert all_finite(c.aux)  # tracking/memory stay sane
        assert rejected > 0

    def test_nan_delta_without_guard_poisons_server(self):
        """Negative control: the same fault with guards OFF does poison
        the server — the guard is what saves it, not an accident."""
        t = make_trainer(fault=FaultConfig(nan_inject_rate=1.0))
        s, c = t.init_state(jax.random.key(0))
        s, c, _ = t.run_round(s, c)
        assert not all_finite(s.params)

    def test_poison_tree_dtypes(self):
        tree = {"f": jnp.ones((3, 2)), "i": jnp.ones((3, 2), jnp.int32)}
        out = poison_tree(tree, jnp.asarray([0., 1., 0.]))
        f = np.asarray(out["f"])
        assert np.all(np.isfinite(f[[0, 2]]))
        assert np.all(np.isnan(f[1]))
        assert int(out["i"][1, 0]) == np.iinfo(np.int32).max


# -- supervisor -------------------------------------------------------------
class TestSupervisor:
    def test_rollback_restores_pre_round_state_bit_exactly(self):
        flt = FaultConfig(nan_inject_rate=1.0, max_retries=2,
                          backoff_base_s=0.0)
        t = make_trainer(fault=flt)
        sup = RoundSupervisor(t, sleep_fn=lambda s: None)
        s, c = t.init_state(jax.random.key(0))
        p0 = jax.tree.map(np.asarray, s.params)
        o0 = jax.tree.map(np.asarray, s.opt)
        s2, c2, m = sup.run_round(s, c)
        for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(s2.params)):
            np.testing.assert_array_equal(a, np.asarray(b))
        for a, b in zip(jax.tree.leaves(o0), jax.tree.leaves(s2.opt)):
            np.testing.assert_array_equal(a, np.asarray(b))
        # round advanced past the skipped round; retries all burned
        assert int(s2.round) == 1
        assert sup.stats.skipped_rounds == 1
        assert sup.stats.retries == flt.max_retries
        assert float(m.online_mask.sum()) == 0.0

    def test_forced_divergence_exactly_one_rollback_and_retry(self):
        """First attempt diverges (stubbed NaN), retry succeeds: exactly
        one rollback + one retry, round completes healthy."""
        t = make_trainer()
        orig = t.run_round
        calls = {"n": 0}

        def flaky(server, clients):
            s, c, m = orig(server, clients)
            calls["n"] += 1
            if calls["n"] == 1:
                s = s._replace(params=jax.tree.map(
                    lambda x: x * jnp.nan, s.params))
            return s, c, m

        t.run_round = flaky
        sup = RoundSupervisor(t, fault=FaultConfig(max_retries=2,
                                                   backoff_base_s=0.0),
                              sleep_fn=lambda s: None)
        s, c = t.init_state(jax.random.key(0))
        s2, c2, m = sup.run_round(s, c)
        assert sup.stats.rollbacks == 1
        assert sup.stats.retries == 1
        assert sup.stats.skipped_rounds == 0
        assert all_finite(s2.params)
        assert int(s2.round) == 1
        assert calls["n"] == 2

    def test_skip_metrics_match_round_metric_shapes(self):
        """Skipped rounds must return [num_clients] metrics exactly like
        healthy rounds, even when the client axis is padded for the
        mesh — stacking a per-round history must never shape-error."""
        flt = FaultConfig(nan_inject_rate=1.0, max_retries=0,
                          backoff_base_s=0.0)
        t = make_trainer(fault=flt, num_clients=10)  # 8-dev mesh pads
        assert t.padded_clients > t.num_clients
        sup = RoundSupervisor(t, sleep_fn=lambda s: None)
        s, c = t.init_state(jax.random.key(0))
        s, c, m_skip = sup.run_round(s, c)
        assert sup.stats.skipped_rounds == 1
        assert m_skip.online_mask.shape == (t.num_clients,)
        assert m_skip.train_loss.shape == (t.num_clients,)

    def test_healthy_rounds_pass_through(self):
        t = make_trainer()
        sup = RoundSupervisor(t, sleep_fn=lambda s: None)
        s, c = t.init_state(jax.random.key(0))
        for _ in range(3):
            s, c, m = sup.run_round(s, c)
        assert sup.stats.rollbacks == 0
        assert sup.stats.healthy_rounds == 3
        assert sup.stats.loss_ema is not None
        assert int(s.round) == 3

    def test_zero_participation_round_does_not_poison_loss_ema(self):
        """An all-crash round carries no loss observation: its 0.0
        must not decay the EMA (which would wedge the blow-up check
        into rejecting every genuine round afterwards), and the round
        loop's scalars must still be reusable from the health fetch."""
        t = make_trainer()
        sup = RoundSupervisor(t, sleep_fn=lambda s: None)
        s, c = t.init_state(jax.random.key(0))
        s, c, m = sup.run_round(s, c)
        ema0 = sup.stats.loss_ema
        assert ema0 is not None and ema0 > 0.0
        assert sup.last_scalars is not None  # one-fetch reuse surface
        assert sup.last_scalars["loss_sum"] > 0.0
        # synthetic zero-participation health report
        sup._note_healthy({"finite": True, "n": 0.0, "loss": 0.0,
                           "round": 2})
        assert sup.stats.loss_ema == ema0  # unchanged
        # and the blow-up check ignores the empty round entirely
        sup.fault = FaultConfig(loss_blowup_factor=2.0)
        assert sup._healthy({"finite": True, "n": 0.0, "loss": 0.0,
                             "round": 3})

    def test_loss_blowup_detection(self):
        """A loss far above the EMA triggers rollback even with finite
        params."""
        t = make_trainer()
        orig = t.run_round
        calls = {"n": 0}

        def blowup(server, clients):
            s, c, m = orig(server, clients)
            calls["n"] += 1
            if calls["n"] == 2:  # second round: loss explodes
                m = m._replace(train_loss=m.train_loss * 1e6)
            return s, c, m

        t.run_round = blowup
        sup = RoundSupervisor(
            t, fault=FaultConfig(loss_blowup_factor=10.0, max_retries=1,
                                 backoff_base_s=0.0),
            sleep_fn=lambda s: None)
        s, c = t.init_state(jax.random.key(0))
        s, c, _ = sup.run_round(s, c)     # healthy, seeds the EMA
        s, c, _ = sup.run_round(s, c)     # blow-up -> rollback, retry ok
        assert sup.stats.rollbacks == 1
        assert sup.stats.healthy_rounds == 2

    def test_persistent_exception_reraises(self):
        t = make_trainer()

        def boom(server, clients):
            raise RuntimeError("xla exploded")

        t.run_round = boom
        sup = RoundSupervisor(t, fault=FaultConfig(max_retries=1,
                                                   backoff_base_s=0.0),
                              sleep_fn=lambda s: None)
        s, c = t.init_state(jax.random.key(0))
        with pytest.raises(RuntimeError, match="xla exploded"):
            sup.run_round(s, c)


# -- diagnostics ------------------------------------------------------------
class TestDiagnostics:
    def test_model_norms_all_finite_flag(self):
        out = model_norms({"w": jnp.ones((3,))})
        assert bool(out["all_finite"])
        out = model_norms({"w": jnp.asarray([1.0, jnp.nan])})
        assert not bool(out["all_finite"])

    def test_model_norms_empty_pytree(self):
        out = model_norms({})
        assert bool(out["all_finite"])
        assert float(out["l2"]) == 0.0
        assert float(out["max_abs"]) == 0.0


# -- checkpoint crash-safety -------------------------------------------------
class TestCheckpointCrashSafety:
    def _roundtrip_setup(self, tmp_path):
        from fedtorch_tpu.utils.checkpoint import save_checkpoint
        t = make_trainer()
        s, c = t.init_state(jax.random.key(0))
        s, c, _ = t.run_round(s, c)
        d = str(tmp_path)
        save_checkpoint(d, s, c, t.cfg, best_prec1=0.5, is_best=False)
        return t, s, c, d

    def test_valid_checkpoint_resumes(self, tmp_path):
        from fedtorch_tpu.utils.checkpoint import maybe_resume
        t, s, c, d = self._roundtrip_setup(tmp_path)
        s0, c0 = t.init_state(jax.random.key(9))
        s2, c2, best, resumed = maybe_resume(d, s0, c0, t.cfg)
        assert resumed and best == 0.5
        assert int(s2.round) == 1

    def test_truncated_checkpoint_skipped(self, tmp_path):
        from fedtorch_tpu.utils.checkpoint import maybe_resume
        t, s, c, d = self._roundtrip_setup(tmp_path)
        path = os.path.join(d, "checkpoint.ckpt")
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[:len(blob) // 2])  # torn write
        s0, c0 = t.init_state(jax.random.key(9))
        with pytest.warns(RuntimeWarning, match="corrupt or truncated"):
            s2, c2, best, resumed = maybe_resume(d, s0, c0, t.cfg)
        assert not resumed

    def test_bitflipped_checkpoint_skipped(self, tmp_path):
        from fedtorch_tpu.utils.checkpoint import maybe_resume
        t, s, c, d = self._roundtrip_setup(tmp_path)
        path = os.path.join(d, "checkpoint.ckpt")
        blob = bytearray(open(path, "rb").read())
        blob[len(blob) // 2] ^= 0xFF  # same length, corrupted content
        with open(path, "wb") as f:
            f.write(bytes(blob))
        s0, c0 = t.init_state(jax.random.key(9))
        with pytest.warns(RuntimeWarning, match="corrupt or truncated"):
            _, _, _, resumed = maybe_resume(d, s0, c0, t.cfg)
        assert not resumed

    def test_indexed_checkpoint_resumes_with_integrity(self, tmp_path):
        """Per-round keeps carry their own integrity frame: resuming an
        OLDER indexed checkpoint after newer saves must still verify and
        succeed (a cross-file record would mismatch the latest meta)."""
        from fedtorch_tpu.utils.checkpoint import (
            maybe_resume, save_checkpoint,
        )
        t = make_trainer()
        s, c = t.init_state(jax.random.key(0))
        d = str(tmp_path)
        s, c, _ = t.run_round(s, c)
        save_checkpoint(d, s, c, t.cfg, 0.1, False, save_some_rounds=(1,))
        s, c, _ = t.run_round(s, c)
        save_checkpoint(d, s, c, t.cfg, 0.2, False)  # newer latest
        s0, c0 = t.init_state(jax.random.key(9))
        s2, _, _, resumed = maybe_resume(d, s0, c0, t.cfg,
                                         checkpoint_index="1")
        assert resumed
        assert int(s2.round) == 1

    def test_missing_meta_still_raises(self, tmp_path):
        from fedtorch_tpu.utils.checkpoint import maybe_resume
        t, s, c, d = self._roundtrip_setup(tmp_path)
        os.remove(os.path.join(d, "checkpoint.json"))
        s0, c0 = t.init_state(jax.random.key(9))
        with pytest.raises(FileNotFoundError):
            maybe_resume(d, s0, c0, t.cfg)

    def test_incompatible_config_still_raises(self, tmp_path):
        from fedtorch_tpu.utils.checkpoint import maybe_resume
        t, s, c, d = self._roundtrip_setup(tmp_path)
        t2 = make_trainer(num_clients=4)
        s0, c0 = t2.init_state(jax.random.key(9))
        with pytest.raises(ValueError, match="incompatible"):
            maybe_resume(d, s0, c0, t2.cfg)


# -- multihost init retry ----------------------------------------------------
class TestInitMultihostRetry:
    def _cfg(self, **kw):
        return MeshConfig(coordinator_address="10.0.0.1:1234",
                          num_processes=2, process_id=0, **kw)

    def test_transient_failure_retries_then_succeeds(self, monkeypatch):
        from fedtorch_tpu.parallel import mesh
        calls = {"n": 0}

        def flaky(**kw):
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError("coordinator not up yet")

        monkeypatch.setattr(jax.distributed, "initialize", flaky)
        delays = []
        mesh.init_multihost(self._cfg(init_backoff_s=0.25),
                            _sleep=delays.append)
        assert calls["n"] == 3
        assert delays == [0.25, 0.5]  # exponential backoff

    def test_timeout_raises_clear_error(self, monkeypatch):
        from fedtorch_tpu.parallel import mesh

        def always_down(**kw):
            raise ConnectionError("nope")

        monkeypatch.setattr(jax.distributed, "initialize", always_down)
        with pytest.raises(RuntimeError, match="10.0.0.1:1234"):
            mesh.init_multihost(
                self._cfg(init_timeout_s=0.5, init_backoff_s=0.3),
                _sleep=lambda d: None)

    def test_permanent_errors_fail_fast(self, monkeypatch):
        from fedtorch_tpu.parallel import mesh
        calls = {"n": 0}

        def malformed(**kw):
            calls["n"] += 1
            raise ValueError("bad coordinator address")

        monkeypatch.setattr(jax.distributed, "initialize", malformed)
        with pytest.raises(ValueError, match="bad coordinator"):
            mesh.init_multihost(self._cfg(), _sleep=lambda d: None)
        assert calls["n"] == 1  # no retry burn on a deterministic error

        def already(**kw):
            # JAX's actual double-init wording (jax/_src/distributed.py)
            raise RuntimeError(
                "distributed.initialize should only be called once.")

        monkeypatch.setattr(jax.distributed, "initialize", already)
        with pytest.raises(RuntimeError, match="only be called once"):
            mesh.init_multihost(self._cfg(), _sleep=lambda d: None)

    def test_no_coordinator_is_noop(self, monkeypatch):
        from fedtorch_tpu.parallel import mesh

        def boom(**kw):
            raise AssertionError("must not be called")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        mesh.init_multihost(MeshConfig())  # no address -> no-op


# -- config validation -------------------------------------------------------
class TestFaultConfigValidation:
    def test_bad_rates_rejected(self):
        with pytest.raises(ValueError, match="client_drop_rate"):
            ExperimentConfig(
                fault=FaultConfig(client_drop_rate=1.5)).finalize()
        with pytest.raises(ValueError, match="straggler_step_frac"):
            ExperimentConfig(
                fault=FaultConfig(straggler_step_frac=0.0)).finalize()
        with pytest.raises(ValueError, match="guard_mode"):
            ExperimentConfig(
                fault=FaultConfig(guard_mode="zap")).finalize()

    def test_cli_flags_map(self):
        from fedtorch_tpu.cli import args_to_config, build_parser
        args = build_parser().parse_args([
            "--federated", "true", "-d", "synthetic",
            "--fault_client_drop_rate", "0.25",
            "--fault_straggler_rate", "0.1",
            "--guard_updates", "true", "--guard_mode", "clip",
            "--supervisor", "true", "--supervisor_max_retries", "3"])
        cfg = args_to_config(args)
        assert cfg.fault.client_drop_rate == 0.25
        assert cfg.fault.straggler_rate == 0.1
        assert cfg.fault.guard_updates
        assert cfg.fault.guard_mode == "clip"
        assert cfg.fault.supervisor
        assert cfg.fault.max_retries == 3
        assert cfg.fault.chaos_enabled
