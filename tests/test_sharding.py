"""Sharded-execution equivalence: the same jitted round program must give
identical results on 1 device and sharded over the 8-device mesh — the
TPU analog of 'centered mode == MPI mode' (SURVEY.md §4 requirement c)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, MeshConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer, make_mesh


def _build(num_devices):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=16,
                        batch_size=16),
        federated=FederatedConfig(federated=True, num_clients=8,
                                  online_client_rate=1.0,
                                  algorithm="fedavg",
                                  sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.2, weight_decay=0.0),
        train=TrainConfig(local_step=3),
        mesh=MeshConfig(num_devices=num_devices),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=16)
    alg = make_algorithm(cfg)
    return FederatedTrainer(cfg, model, alg, data.train)


def test_single_vs_eight_devices():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    t1 = _build(num_devices=1)
    t8 = _build(num_devices=8)
    assert t8.mesh.devices.size == 8

    s1, c1 = t1.init_state(jax.random.key(42))
    s8, c8 = t8.init_state(jax.random.key(42))
    for _ in range(3):
        s1, c1, m1 = t1.run_round(s1, c1)
        s8, c8, m8 = t8.run_round(s8, c8)

    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s8.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m1.train_loss),
                               np.asarray(m8.train_loss), atol=1e-5)


def test_client_state_sharded():
    t8 = _build(num_devices=8)
    s8, c8 = t8.init_state(jax.random.key(0))
    leaf = jax.tree.leaves(c8.params)[0]
    assert len(leaf.sharding.device_set) == 8
