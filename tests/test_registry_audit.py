"""The registry-drift checker (ISSUE 13, FTC rules).

Two layers:

* **seeded diffs/extractions** — each FTC rule fires on a seeded
  violation (phantom metrics field, undocumented event, missing seam
  row, unconsumed CLI flag, unknown illegal cell) through the same
  pure extraction/diff functions the audit composes;
* **zero drift at head** — ``audit_registries(repo_root)`` must come
  back EMPTY on the checked-in tree: emit sites ⊆ catalogs, catalogs
  ⊆ emit sites (or reserved), every seam drilled and documented,
  every CLI flag consumed, every illegal cell snapshot-tested. This
  is the tier-1 gate every later PR inherits.

Also pins the docs tables in docs/static_analysis.md against
``rules.markdown_table`` so the rendered rule catalog cannot drift
from the registry.
"""
import os

from fedtorch_tpu.lint.registry_audit import (
    audit_registries, axis_tuples, consumed_args, diff_builder_cells,
    diff_config_cli, diff_event_names, diff_metric_fields,
    documented_event_names, documented_row_fields, documented_seams,
    emitted_event_names_from_source, emitted_row_fields_from_source,
    illegal_cells, parser_dests,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- seeded violations -------------------------------------------------------

class TestSeededFTC001:
    def test_phantom_emitted_field(self):
        fs = diff_metric_fields(
            emitted={"round", "my_new_gauge"},
            cataloged={"round"}, documented={"round"})
        assert any(f.rule == "FTC001" and "my_new_gauge" in f.message
                   and "not cataloged" in f.message for f in fs)

    def test_cataloged_never_emitted(self):
        fs = diff_metric_fields(
            emitted={"round"}, cataloged={"round", "ghost"},
            documented={"round", "ghost"})
        assert any("ghost" in f.message and "no emit site" in f.message
                   for f in fs)
        # reserved names are exempt
        fs = diff_metric_fields(
            emitted={"round"}, cataloged={"round", "ghost"},
            documented={"round", "ghost"}, reserved=("ghost",))
        assert fs == []

    def test_undocumented_field(self):
        fs = diff_metric_fields(
            emitted={"round", "new_gauge"},
            cataloged={"round", "new_gauge"}, documented={"round"})
        assert any("new_gauge" in f.message and "missing from the"
                   in f.message for f in fs)

    def test_row_field_extraction(self):
        src = (
            "def loop():\n"
            "    row = {'round': r, 'loss': l}\n"
            "    row['extra_s'] = 1.0\n"
            "    row.update(sup_retries=2.0)\n"
            "    row.update({'host_faults': 3.0})\n"
            "class C:\n"
            "    def stats(self):\n"
            "        out = {'ckpt_writes': 1.0}\n"
            "        out['ckpt_queue_depth'] = 0.0\n"
            "        return out\n")
        assert emitted_row_fields_from_source(src) == {
            "round", "loss", "extra_s", "sup_retries", "host_faults",
            "ckpt_writes", "ckpt_queue_depth"}


class TestSeededFTC002:
    def test_event_extraction_and_diff(self):
        src = ("tel.event('run.start', round=0)\n"
               "telemetry.event('chaos.host_fault', seam=s)\n")
        emitted = emitted_event_names_from_source(src)
        assert emitted == {"run.start", "chaos.host_fault"}
        fs = diff_event_names(emitted, {"run.start"})
        assert any(f.rule == "FTC002" and "chaos.host_fault" in f.message
                   for f in fs)
        fs = diff_event_names({"run.start"},
                              {"run.start", "ghost.event"})
        assert any("ghost.event" in f.message and "no emit site"
                   in f.message for f in fs)

    def test_doc_event_section_extraction(self):
        doc = ("Events (`events.jsonl`): `run.start`, `run.end`, and\n"
               "`host.recovered` (see `robustness.md` and `schema.py`).\n"
               "\n## Span taxonomy\n`stream.gather` spans\n")
        names = documented_event_names(doc)
        assert names == {"run.start", "run.end", "host.recovered"}


class TestSeededFTC003:
    def test_seam_table_extraction(self):
        md = ("| seam | site |\n|---|---|\n"
              "| `stream.gather` | producer |\n"
              "| `ckpt.write` | writer |\n"
              "| *(producer death)* | any |\n")
        assert documented_seams(md) == {"stream.gather", "ckpt.write"}


class TestSeededFTC004:
    def test_unconsumed_and_phantom_dests(self):
        src = (
            "def build_parser():\n"
            "    p.add_argument('--lr', type=float)\n"
            "    p.add_argument('--dead_flag', type=int)\n"
            "    p.add_argument('-j', '--workers', dest='num_workers')\n"
            "def args_to_config(args):\n"
            "    return (args.lr, args.num_workers, args.phantom)\n")
        dests, used = parser_dests(src), consumed_args(src)
        assert dests.keys() == {"lr", "dead_flag", "num_workers"}
        fs = diff_config_cli(dests, used, non_config=())
        msgs = "\n".join(f.message for f in fs)
        assert "dead_flag" in msgs and "phantom" in msgs
        assert all(f.rule == "FTC004" for f in fs)

    def test_clean_surface_passes(self):
        src = (
            "def build_parser():\n"
            "    p.add_argument('--lr', type=float)\n"
            "def args_to_config(args):\n"
            "    return args.lr\n")
        assert diff_config_cli(parser_dests(src), consumed_args(src),
                               non_config=()) == []


class TestSeededFTC005:
    AXES_SRC = ("SOURCES = ('resident', 'feed')\n"
                "DISPATCHES = ('round', 'scan', 'commit')\n"
                "EXECUTIONS = ('vmap', 'fused')\n")

    def test_unknown_axis_value_in_illegal_cell(self):
        test_src = ("ILLEGAL = {('resident', 'warp', 'fused')}\n"
                    "iter_cells\n")
        fs = diff_builder_cells(axis_tuples(self.AXES_SRC),
                                illegal_cells(test_src), test_src)
        assert any(f.rule == "FTC005" and "warp" in f.message
                   for f in fs)

    def test_missing_refusal_snapshot(self):
        test_src = ("ILLEGAL = {('resident', 'commit', 'fused')}\n"
                    "iter_cells\n")  # no '(resident x commit x fused)'
        fs = diff_builder_cells(axis_tuples(self.AXES_SRC),
                                illegal_cells(test_src), test_src)
        assert any("refusal-message snapshot" in f.message for f in fs)

    def test_snapshot_plus_enumeration_passes(self):
        test_src = ("ILLEGAL = {('resident', 'commit', 'fused')}\n"
                    "iter_cells\n"
                    "# pins '(resident x commit x fused)' exactly\n")
        assert diff_builder_cells(axis_tuples(self.AXES_SRC),
                                  illegal_cells(test_src),
                                  test_src) == []


# -- zero drift at head ------------------------------------------------------

def test_zero_registry_drift_at_head():
    """The checked-in tree must be drift-free: the checker lands green
    with an EMPTY baseline (ISSUE 13 acceptance), so any future
    uncataloged gauge, undocumented event/seam, dead CLI flag or
    unsnapshotted illegal cell fails tier-1 here."""
    findings = audit_registries(REPO)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_docs_tables_match_rules_registry():
    """docs/static_analysis.md embeds the FTP/FTC tables rendered from
    rules.py — byte-for-byte, so the docs cannot drift from the
    registry (the tables are generated, not hand-maintained)."""
    from fedtorch_tpu.lint.rules import (
        CONCURRENCY_RULES, PROGRAM_RULES, REGISTRY_RULES, markdown_table,
    )
    doc = open(os.path.join(REPO, "docs/static_analysis.md")).read()
    assert markdown_table(CONCURRENCY_RULES) in doc
    assert markdown_table(PROGRAM_RULES) in doc
    assert markdown_table(REGISTRY_RULES) in doc


# -- FTC006: lint-rule docs drift --------------------------------------------

def test_ftc006_missing_fth_id_flagged():
    """A registered FTH id absent from the docs tables is FTC006."""
    from fedtorch_tpu.lint.registry_audit import (
        diff_rule_docs, documented_rule_ids,
    )
    doc = "| `FTH001` | x | y |\n| `FTP001` | x | y |\n"
    fs = diff_rule_docs({"FTH001", "FTH002", "FTP001"},
                        documented_rule_ids(doc))
    assert [f.rule for f in fs] == ["FTC006"]
    assert "FTH002" in fs[0].message


def test_ftc006_documented_ids_pass():
    from fedtorch_tpu.lint.registry_audit import (
        diff_rule_docs, documented_rule_ids,
    )
    doc = "| `FTH001` | x |\n| `FTH002` | y |\n"
    assert diff_rule_docs({"FTH001", "FTH002"},
                          documented_rule_ids(doc)) == []


def test_head_doc_field_extraction_is_sane():
    """Guard the extraction itself: the docs metric catalog must yield
    a plausibly-sized field set (an empty set would make the
    documented-direction checks vacuously green)."""
    doc = open(os.path.join(REPO, "docs/observability.md")).read()
    fields = documented_row_fields(doc)
    assert {"round", "loss", "model_flops_utilization",
            "ckpt_total_write_s"} <= fields
    assert len(fields) > 30
