"""Pallas kernel tests (interpret mode on CPU; the real TPU lowering uses
the same kernel body)."""
import functools

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.ops.pallas.quant_kernel import _LANE, _qdq_kernel, \
    fused_quantize_dequantize
from fedtorch_tpu.ops.quantize import quantize_dequantize


def _run_interpret(x, num_bits=8):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    n = x.size
    rows = -(-(-(-n // _LANE)) // 8) * 8
    padded = jnp.zeros((rows * _LANE,), jnp.float32).at[:n].set(
        x.reshape(-1))
    out = pl.pallas_call(
        functools.partial(_qdq_kernel, num_bits=num_bits),
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=True,
    )(jnp.asarray([n], jnp.int32), padded.reshape(rows, _LANE))
    return np.asarray(out).reshape(-1)[:n].reshape(x.shape)


@pytest.mark.parametrize("n,bits", [(100, 8), (1000, 8), (1000, 16),
                                    (128, 8)])
def test_kernel_matches_xla_path(n, bits):
    rng = np.random.RandomState(n)
    x = jnp.asarray(rng.randn(n).astype(np.float32) * 3)
    got = _run_interpret(x, bits)
    want = np.asarray(quantize_dequantize(x, bits))
    # reduction-order fp differences stay far below one quantization bin
    np.testing.assert_allclose(got, want, atol=5e-6)


def test_constant_tensor():
    x = jnp.full((200,), 2.5)
    got = _run_interpret(x)
    np.testing.assert_allclose(got, np.asarray(x), atol=1e-3)


def test_padding_does_not_leak_into_stats():
    """Padded zeros must not perturb min/max/mean: compare a tensor whose
    true min/max exclude 0."""
    x = jnp.asarray(np.linspace(5.0, 9.0, 777, dtype=np.float32))
    got = _run_interpret(x)
    want = np.asarray(quantize_dequantize(x, 8))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_fallback_on_cpu():
    """On CPU the public wrapper silently uses the XLA path."""
    x = jnp.asarray(np.random.RandomState(0).randn(64).astype(np.float32))
    out = fused_quantize_dequantize(x, 8)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(quantize_dequantize(x, 8)),
                               atol=1e-7)
