"""Pallas kernel tests (interpret mode on CPU; the real TPU lowering uses
the same kernel body)."""
import functools

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.ops.pallas.quant_kernel import _LANE, _qdq_kernel, \
    fused_quantize_dequantize
from fedtorch_tpu.ops.quantize import quantize_dequantize


def _run_interpret(x, num_bits=8):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    n = x.size
    rows = -(-(-(-n // _LANE)) // 8) * 8
    padded = jnp.zeros((rows * _LANE,), jnp.float32).at[:n].set(
        x.reshape(-1))
    out = pl.pallas_call(
        functools.partial(_qdq_kernel, num_bits=num_bits),
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=True,
    )(jnp.asarray([n], jnp.int32), padded.reshape(rows, _LANE))
    return np.asarray(out).reshape(-1)[:n].reshape(x.shape)


@pytest.mark.parametrize("n,bits", [(100, 8), (1000, 8), (1000, 16),
                                    (128, 8)])
def test_kernel_matches_xla_path(n, bits):
    rng = np.random.RandomState(n)
    x = jnp.asarray(rng.randn(n).astype(np.float32) * 3)
    got = _run_interpret(x, bits)
    want = np.asarray(quantize_dequantize(x, bits))
    # reduction-order fp differences stay far below one quantization bin
    np.testing.assert_allclose(got, want, atol=5e-6)


def test_constant_tensor():
    x = jnp.full((200,), 2.5)
    got = _run_interpret(x)
    np.testing.assert_allclose(got, np.asarray(x), atol=1e-3)


def test_padding_does_not_leak_into_stats():
    """Padded zeros must not perturb min/max/mean: compare a tensor whose
    true min/max exclude 0."""
    x = jnp.asarray(np.linspace(5.0, 9.0, 777, dtype=np.float32))
    got = _run_interpret(x)
    want = np.asarray(quantize_dequantize(x, 8))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_fallback_on_cpu():
    """On CPU the public wrapper silently uses the XLA path."""
    x = jnp.asarray(np.random.RandomState(0).randn(64).astype(np.float32))
    out = fused_quantize_dequantize(x, 8)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(quantize_dequantize(x, 8)),
                               atol=1e-7)


class TestBatchKernel:
    """Client-grid uplink kernel: per-slice stats over the leading axis."""

    @pytest.mark.parametrize("C,n,bits", [(4, 100, 8), (3, 1000, 16),
                                          (8, 128, 8), (1, 50, 8)])
    def test_grid_matches_vmapped_xla(self, C, n, bits):
        from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_batch
        rng = np.random.RandomState(C * n)
        # distinct per-client scales so shared stats would show up loudly
        x = jnp.asarray(rng.randn(C, n).astype(np.float32)
                        * np.arange(1, C + 1)[:, None])
        got = np.asarray(fused_quantize_dequantize_batch(
            x, bits, force_pallas=True, interpret=True))
        want = np.asarray(jax.vmap(
            lambda v: quantize_dequantize(v, bits))(x))
        np.testing.assert_allclose(got, want, atol=5e-6)

    def test_grid_preserves_tensor_shape(self):
        from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_batch
        x = jnp.asarray(np.random.RandomState(1).randn(
            3, 4, 5, 2).astype(np.float32))
        out = fused_quantize_dequantize_batch(x, 8, force_pallas=True,
                                              interpret=True)
        assert out.shape == x.shape
        want = jax.vmap(lambda v: quantize_dequantize(v, 8))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=5e-6)

    def test_cpu_fallback_matches(self):
        from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_batch
        x = jnp.asarray(np.random.RandomState(2).randn(
            5, 64).astype(np.float32))
        out = fused_quantize_dequantize_batch(x, 8)  # CPU -> XLA vmap
        want = jax.vmap(lambda v: quantize_dequantize(v, 8))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-7)

    def test_engine_uplink_routes_through_batch_transform(self):
        """A quantized fedavg round must produce payloads on the
        per-client quantization grid: monkeypatch the batch transform to
        count invocations and verify the engine calls it once."""
        from fedtorch_tpu.algorithms import make_algorithm
        from fedtorch_tpu.config import (
            DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
            ModelConfig, OptimConfig, TrainConfig,
        )
        from fedtorch_tpu.data import build_federated_data
        from fedtorch_tpu.models import define_model
        from fedtorch_tpu.parallel import FederatedTrainer

        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=12,
                            batch_size=8),
            federated=FederatedConfig(federated=True, num_clients=4,
                                      online_client_rate=1.0,
                                      algorithm="fedavg", quantized=True,
                                      sync_type="local_step"),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.1, weight_decay=0.0),
            train=TrainConfig(local_step=2),
            mesh=MeshConfig(num_devices=1),
        ).finalize()
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=8)
        alg = make_algorithm(cfg)
        calls = []
        orig = alg.payload_batch_transform
        alg.payload_batch_transform = lambda p: calls.append(1) or orig(p)
        t = FederatedTrainer(cfg, model, alg, data.train)
        server, clients = t.init_state(jax.random.key(0))
        server, clients, m = t.run_round(server, clients)
        assert calls, "engine never invoked payload_batch_transform"
        assert np.isfinite(float(m.train_loss.sum()))


class TestTiledKernel:
    """Two-pass grid-tiled kernel for payloads past the single-block
    VMEM ceiling (real-TPU scoped-vmem limit is ~786k f32 elems)."""

    def _run_tiled(self, x, bits=8):
        from fedtorch_tpu.ops.pallas.quant_kernel import (
            _LANE, _TILE_ROWS, _pallas_qdq_tiled)
        n = x.size
        rows = -(-n // _LANE)
        rows = -(-rows // _TILE_ROWS) * _TILE_ROWS
        padded = jnp.zeros((rows * _LANE,), jnp.float32).at[:n].set(
            x.reshape(-1))
        out = _pallas_qdq_tiled(padded.reshape(rows, _LANE),
                                jnp.asarray([n], jnp.int32), bits,
                                interpret=True)
        return np.asarray(out).reshape(-1)[:n].reshape(x.shape)

    @pytest.mark.parametrize("n,bits", [(200_000, 8), (200_000, 16),
                                        (65_536, 8)])
    def test_matches_xla_within_one_bin(self, n, bits):
        # Block-sequential stat accumulation reorders the mean sum, which
        # can flip bin-boundary elements by exactly one bin; everything
        # else must agree.
        rng = np.random.RandomState(n % 1000)
        x = jnp.asarray(rng.randn(n).astype(np.float32) * 2)
        got = self._run_tiled(x, bits)
        want = np.asarray(quantize_dequantize(x, bits))
        bin_w = (float(x.max()) - float(x.min())) / (2 ** bits - 1)
        assert np.abs(got - want).max() < 1.05 * bin_w
        # boundary flips must be rare: stats agree to ~ulp, so <0.1% of
        # elements may move a bin
        frac = np.mean(np.abs(got - want) > 0.51 * bin_w)
        assert frac < 1e-3

    def test_multi_block_padding_excluded_from_stats(self):
        # 70_000 elems -> 2 blocks of (512,128) with a padded tail; a
        # positive-only payload detects zero-padding leaking into min
        x = jnp.asarray(np.linspace(5.0, 9.0, 70_000, dtype=np.float32))
        got = self._run_tiled(x)
        want = np.asarray(quantize_dequantize(x, 8))
        # a zero leaking into min would shift every output by ~5.0 (the
        # affine grid would span [0, 9]); one-bin flips at linspace's
        # exact bin boundaries are the only acceptable difference
        bin_w = 4.0 / 255
        assert np.abs(got - want).max() < 1.05 * bin_w
        assert np.mean(np.abs(got - want) > 0.51 * bin_w) < 1e-3


class TestTreeTransform:
    """Size-bucketed whole-tree quantization (one grid launch per
    distinct leaf size, per-tensor stats preserved)."""

    def _tree(self, rng, lead=None):
        shp = lambda *s: (lead, *s) if lead else s
        return {
            "conv1": jnp.asarray(rng.randn(*shp(3, 3, 4)).astype(np.float32)),
            "conv2": jnp.asarray(
                rng.randn(*shp(6, 2, 3)).astype(np.float32) * 5),
            "bias": jnp.asarray(rng.randn(*shp(16,)).astype(np.float32)),
            "bias2": jnp.asarray(rng.randn(*shp(16,)).astype(np.float32) * 9),
        }

    def test_matches_per_leaf_xla(self):
        from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_tree
        tree = self._tree(np.random.RandomState(0))
        got = fused_quantize_dequantize_tree(tree, 8)
        want = jax.tree.map(lambda x: quantize_dequantize(x, 8), tree)
        for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=1e-6)
            assert g.shape == w.shape and g.dtype == w.dtype

    def test_leading_batch_per_client_stats(self):
        from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_tree
        tree = self._tree(np.random.RandomState(1), lead=3)
        got = fused_quantize_dequantize_tree(tree, 8, leading_batch=True)
        want = jax.tree.map(
            lambda x: jax.vmap(lambda v: quantize_dequantize(v, 8))(x), tree)
        for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=1e-6)

    def test_under_vmap_falls_back(self):
        """Called with batch tracers (inside the client vmap) the tree
        transform must still be correct via the XLA fallback."""
        from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_tree
        tree = self._tree(np.random.RandomState(2), lead=4)
        got = jax.vmap(
            lambda t: fused_quantize_dequantize_tree(t, 8))(tree)
        want = jax.tree.map(
            lambda x: jax.vmap(lambda v: quantize_dequantize(v, 8))(x), tree)
        for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=1e-6)

    def test_empty_tree(self):
        from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_tree
        assert fused_quantize_dequantize_tree({}, 8) == {}

    def test_bucket_path_reachable_in_interpret_mode(self, monkeypatch):
        """Exercise the TPU bucket/stack/unstack code (not the CPU
        per-leaf fallback) via force_pallas+interpret, including the
        oversize branch (per-slice size past the VMEM ceiling) with the
        ceiling shrunk so small arrays take it."""
        import fedtorch_tpu.ops.pallas.quant_kernel as qk
        monkeypatch.setattr(qk, "_MAX_VMEM_ELEMS", 256)
        rng = np.random.RandomState(5)
        tree = {
            # bucketable pair (same size) under the shrunk ceiling
            "a": jnp.asarray(rng.randn(200).astype(np.float32)),
            "b": jnp.asarray(rng.randn(200).astype(np.float32) * 4),
            # oversize leaf -> per-leaf fused path
            "big": jnp.asarray(rng.randn(700).astype(np.float32)),
        }
        got = qk.fused_quantize_dequantize_tree(
            tree, 8, force_pallas=True, interpret=True)
        want = jax.tree.map(lambda x: quantize_dequantize(x, 8), tree)
        for k in tree:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(want[k]), atol=5e-6)

        # leading_batch layout: oversize slices go through the per-slice
        # fused loop; per-client stats must hold
        up = {"w": jnp.asarray(rng.randn(3, 700).astype(np.float32)
                               * np.arange(1, 4)[:, None])}
        got_u = qk.fused_quantize_dequantize_tree(
            up, 8, leading_batch=True, force_pallas=True, interpret=True)
        want_u = jax.tree.map(
            lambda x: jax.vmap(lambda v: quantize_dequantize(v, 8))(x), up)
        np.testing.assert_allclose(np.asarray(got_u["w"]),
                                   np.asarray(want_u["w"]), atol=5e-6)
