"""Pallas kernel tests (interpret mode on CPU; the real TPU lowering uses
the same kernel body)."""
import functools

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.ops.pallas.quant_kernel import _LANE, _qdq_kernel, \
    fused_quantize_dequantize
from fedtorch_tpu.ops.quantize import quantize_dequantize


def _run_interpret(x, num_bits=8):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    n = x.size
    rows = -(-(-(-n // _LANE)) // 8) * 8
    padded = jnp.zeros((rows * _LANE,), jnp.float32).at[:n].set(
        x.reshape(-1))
    out = pl.pallas_call(
        functools.partial(_qdq_kernel, num_bits=num_bits),
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=True,
    )(jnp.asarray([n], jnp.int32), padded.reshape(rows, _LANE))
    return np.asarray(out).reshape(-1)[:n].reshape(x.shape)


@pytest.mark.parametrize("n,bits", [(100, 8), (1000, 8), (1000, 16),
                                    (128, 8)])
def test_kernel_matches_xla_path(n, bits):
    rng = np.random.RandomState(n)
    x = jnp.asarray(rng.randn(n).astype(np.float32) * 3)
    got = _run_interpret(x, bits)
    want = np.asarray(quantize_dequantize(x, bits))
    # reduction-order fp differences stay far below one quantization bin
    np.testing.assert_allclose(got, want, atol=5e-6)


def test_constant_tensor():
    x = jnp.full((200,), 2.5)
    got = _run_interpret(x)
    np.testing.assert_allclose(got, np.asarray(x), atol=1e-3)


def test_padding_does_not_leak_into_stats():
    """Padded zeros must not perturb min/max/mean: compare a tensor whose
    true min/max exclude 0."""
    x = jnp.asarray(np.linspace(5.0, 9.0, 777, dtype=np.float32))
    got = _run_interpret(x)
    want = np.asarray(quantize_dequantize(x, 8))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_fallback_on_cpu():
    """On CPU the public wrapper silently uses the XLA path."""
    x = jnp.asarray(np.random.RandomState(0).randn(64).astype(np.float32))
    out = fused_quantize_dequantize(x, 8)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(quantize_dequantize(x, 8)),
                               atol=1e-7)


class TestBatchKernel:
    """Client-grid uplink kernel: per-slice stats over the leading axis."""

    @pytest.mark.parametrize("C,n,bits", [(4, 100, 8), (3, 1000, 16),
                                          (8, 128, 8), (1, 50, 8)])
    def test_grid_matches_vmapped_xla(self, C, n, bits):
        from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_batch
        rng = np.random.RandomState(C * n)
        # distinct per-client scales so shared stats would show up loudly
        x = jnp.asarray(rng.randn(C, n).astype(np.float32)
                        * np.arange(1, C + 1)[:, None])
        got = np.asarray(fused_quantize_dequantize_batch(
            x, bits, force_pallas=True, interpret=True))
        want = np.asarray(jax.vmap(
            lambda v: quantize_dequantize(v, bits))(x))
        np.testing.assert_allclose(got, want, atol=5e-6)

    def test_grid_preserves_tensor_shape(self):
        from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_batch
        x = jnp.asarray(np.random.RandomState(1).randn(
            3, 4, 5, 2).astype(np.float32))
        out = fused_quantize_dequantize_batch(x, 8, force_pallas=True,
                                              interpret=True)
        assert out.shape == x.shape
        want = jax.vmap(lambda v: quantize_dequantize(v, 8))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=5e-6)

    def test_cpu_fallback_matches(self):
        from fedtorch_tpu.ops.pallas import fused_quantize_dequantize_batch
        x = jnp.asarray(np.random.RandomState(2).randn(
            5, 64).astype(np.float32))
        out = fused_quantize_dequantize_batch(x, 8)  # CPU -> XLA vmap
        want = jax.vmap(lambda v: quantize_dequantize(v, 8))(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-7)

    def test_engine_uplink_routes_through_batch_transform(self):
        """A quantized fedavg round must produce payloads on the
        per-client quantization grid: monkeypatch the batch transform to
        count invocations and verify the engine calls it once."""
        from fedtorch_tpu.algorithms import make_algorithm
        from fedtorch_tpu.config import (
            DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
            ModelConfig, OptimConfig, TrainConfig,
        )
        from fedtorch_tpu.data import build_federated_data
        from fedtorch_tpu.models import define_model
        from fedtorch_tpu.parallel import FederatedTrainer

        cfg = ExperimentConfig(
            data=DataConfig(dataset="synthetic", synthetic_dim=12,
                            batch_size=8),
            federated=FederatedConfig(federated=True, num_clients=4,
                                      online_client_rate=1.0,
                                      algorithm="fedavg", quantized=True,
                                      sync_type="local_step"),
            model=ModelConfig(arch="logistic_regression"),
            optim=OptimConfig(lr=0.1, weight_decay=0.0),
            train=TrainConfig(local_step=2),
            mesh=MeshConfig(num_devices=1),
        ).finalize()
        data = build_federated_data(cfg)
        model = define_model(cfg, batch_size=8)
        alg = make_algorithm(cfg)
        calls = []
        orig = alg.payload_batch_transform
        alg.payload_batch_transform = lambda p: calls.append(1) or orig(p)
        t = FederatedTrainer(cfg, model, alg, data.train)
        server, clients = t.init_state(jax.random.key(0))
        server, clients, m = t.run_round(server, clients)
        assert calls, "engine never invoked payload_batch_transform"
        assert np.isfinite(float(m.train_loss.sum()))
