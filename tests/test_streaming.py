"""Streaming data plane (``cfg.data.data_plane='stream'``): bitwise
parity with the device plane (FedAvg + SCAFFOLD, chaos on and off, both
sync modes), device residency bounded by the double-buffered feed,
exactly-once tracing of the streamed round program, native-vs-numpy
feed-packer parity, and the host-replay lifecycle (invalidate/resume,
supervisor rollback resync)."""
import dataclasses
import gc
import threading

import jax
import numpy as np
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FaultConfig, FederatedConfig,
    ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.data.batching import ClientData
from fedtorch_tpu.data.streaming import (
    HostClientStore, RoundFeed, feed_nbytes,
)
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.utils.tracing import (
    RecompilationSentinel, live_buffer_summary,
)

CHAOS = {"client_drop_rate": 0.3, "straggler_rate": 0.3,
         "nan_inject_rate": 0.3, "guard_updates": True}


def make_cfg(plane, algorithm="fedavg", fault_kw=None, sync="local_step",
             num_epochs_per_comm=1, local_step=5, batch_size=16,
             num_clients=8, online_rate=0.5, **fed_kw):
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20,
                        batch_size=batch_size, synthetic_alpha=0.5,
                        synthetic_beta=0.5, data_plane=plane),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients,
            online_client_rate=online_rate, algorithm=algorithm,
            sync_type=sync, num_epochs_per_comm=num_epochs_per_comm,
            **fed_kw),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.3, weight_decay=0.0),
        train=TrainConfig(local_step=local_step),
        fault=FaultConfig(**(fault_kw or {})),
    ).finalize()


def build(plane, **kw):
    cfg = make_cfg(plane, **kw)
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)


def assert_trees_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


# -- bitwise parity with the device plane ------------------------------------
@pytest.mark.parametrize("algorithm,fault_kw", [
    ("fedavg", None),
    ("fedavg", CHAOS),          # chaos + guards ride the same streams
    ("scaffold", None),
    ("scaffold", CHAOS),
])
def test_stream_matches_device_bitwise(algorithm, fault_kw):
    """Server params, full client state (incl. SCAFFOLD control
    variates), and metrics must match the device plane BITWISE over
    multiple rounds — the acceptance contract of the streaming plane."""
    t_dev = build("device", algorithm=algorithm, fault_kw=fault_kw)
    t_str = build("stream", algorithm=algorithm, fault_kw=fault_kw)
    assert t_str.data is None and t_str.host_store is not None
    s1, c1 = t_dev.init_state(jax.random.key(3))
    s2, c2 = t_str.init_state(jax.random.key(3))
    for _ in range(3):
        s1, c1, m1 = t_dev.run_round(s1, c1)
        s2, c2, m2 = t_str.run_round(s2, c2)
    assert_trees_equal((s1.params, s1.aux, c1), (s2.params, s2.aux, c2))
    assert_trees_equal(m1, m2)
    t_str.invalidate_stream()


def test_stream_matches_device_shard_path_epoch_sync():
    """Epoch-sync device mode auto-resolves gather_mode='shard'; the
    streamed rows (always the 'batch' plan) must still match it
    bitwise — the row plan IS the shard-mode batch order flattened."""
    t_dev = build("device", sync="epoch", num_epochs_per_comm=2)
    t_str = build("stream", sync="epoch", num_epochs_per_comm=2)
    assert t_dev.gather_mode == "shard"
    assert t_str.gather_mode == "batch"
    s1, c1 = t_dev.init_state(jax.random.key(7))
    s2, c2 = t_str.init_state(jax.random.key(7))
    for _ in range(2):
        s1, c1, m1 = t_dev.run_round(s1, c1)
        s2, c2, m2 = t_str.run_round(s2, c2)
    assert_trees_equal((s1.params, c1.params), (s2.params, c2.params))
    t_str.invalidate_stream()


def test_stream_resyncs_after_invalidate_mid_run():
    """Dropping the producer mid-run (the supervisor-rollback /
    resume-into-live-trainer path) must re-sync from device state and
    continue the exact trajectory."""
    t_dev = build("device")
    t_str = build("stream")
    s1, c1 = t_dev.init_state(jax.random.key(0))
    s2, c2 = t_str.init_state(jax.random.key(0))
    for r in range(4):
        s1, c1, _ = t_dev.run_round(s1, c1)
        s2, c2, _ = t_str.run_round(s2, c2)
        if r == 1:
            t_str.invalidate_stream()  # all prefetched feeds dropped
    assert_trees_equal(s1.params, s2.params)
    t_str.invalidate_stream()


# -- producer behavior -------------------------------------------------------
def test_producer_prefetches_ahead_and_drains():
    t = build("stream", local_step=2, batch_size=8, online_rate=0.25)
    server, clients = t.init_state(jax.random.key(0))
    server, clients, _ = t.run_round(server, clients)
    jax.block_until_ready(server.params)
    assert any(th.name == "stream-feed-producer"
               for th in threading.enumerate())
    # double-buffered: by the time round 0 finished, later rounds'
    # feeds were (or are being) produced ahead of consumption
    assert t._stream.rounds_produced >= 2
    t.invalidate_stream()
    assert not any(th.name == "stream-feed-producer" and th.is_alive()
                   for th in threading.enumerate())
    assert t._stream is None


def test_dropped_trainer_does_not_leak_producer():
    """A stream-plane trainer dropped WITHOUT invalidate_stream must
    not orphan the producer thread (which would pin the host store
    and the placed feeds for the rest of the process): the weakref
    finalizer closes the stream when the trainer is collected."""
    import time
    t = build("stream", local_step=2, batch_size=8, online_rate=0.25)
    server, clients = t.init_state(jax.random.key(0))
    server, clients, _ = t.run_round(server, clients)
    jax.block_until_ready(server.params)
    assert any(th.name == "stream-feed-producer" and th.is_alive()
               for th in threading.enumerate())
    del t, server, clients
    gc.collect()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if not any(th.name == "stream-feed-producer" and th.is_alive()
                   for th in threading.enumerate()):
            break
        time.sleep(0.1)
    assert not any(th.name == "stream-feed-producer" and th.is_alive()
                   for th in threading.enumerate())


def test_stream_round_traces_exactly_once():
    """The recompilation sentinel on the streamed round program: feed
    shapes are static, so 4 rounds = 1 trace (the 'static config =>
    unchanged traced program' contract, docs/static_analysis.md)."""
    t = build("stream")
    server, clients = t.init_state(jax.random.key(1))
    with RecompilationSentinel() as s:
        for _ in range(4):
            server, clients, _ = t.run_round(server, clients)
        jax.block_until_ready(server.params)
    s.assert_traces(t.stream_trace_name, expected=1)
    t.invalidate_stream()


# -- device residency --------------------------------------------------------
def test_device_holds_feed_not_store():
    """The residency contract: under 'stream' no device array holds the
    full [C, n_max, ...] client store — only feed-sized buffers (at
    most the prefetch depth + the round in flight) — and total live
    device bytes drop below the device plane's."""
    kw = dict(local_step=2, batch_size=8, online_rate=0.25)

    gc.collect()
    base = live_buffer_summary()["total_bytes"]
    t_dev = build("device", **kw)
    server, clients = t_dev.init_state(jax.random.key(0))
    for _ in range(2):
        server, clients, _ = t_dev.run_round(server, clients)
    jax.block_until_ready(server.params)
    summary = live_buffer_summary()
    dev_bytes = summary["total_bytes"] - base
    store_shape = tuple(t_dev.data.x.shape)
    store_key = f"{store_shape}:{t_dev.data.x.dtype}"
    assert store_key in summary["by_shape"]  # full store is resident
    del t_dev, server, clients
    gc.collect()

    base = live_buffer_summary()["total_bytes"]
    t_str = build("stream", **kw)
    server, clients = t_str.init_state(jax.random.key(0))
    for _ in range(2):
        server, clients, _ = t_str.run_round(server, clients)
    jax.block_until_ready(server.params)
    summary = live_buffer_summary()
    str_bytes = summary["total_bytes"] - base
    # the full client store must NOT be resident on device...
    assert store_key not in summary["by_shape"]
    # ...only packed feeds: [k, K*B, ...], bounded by the double
    # buffer (queue depth 2) + the feed in flight + one being placed
    k, rows = t_str.k_online, t_str.local_steps * t_str.batch_size
    feed_key = f"{(k, rows, 20)}:float32"
    n_feeds = summary["by_shape"].get(feed_key, 0) \
        / (k * rows * 20 * 4 * jax.device_count())
    assert n_feeds <= 4
    # and the streamed footprint undercuts the device-resident one
    assert str_bytes < dev_bytes
    t_str.invalidate_stream()


# -- feed packer: native vs numpy bitwise parity -----------------------------
def _toy_store():
    rng = np.random.RandomState(0)
    C, n_max, F = 5, 12, 3
    x = rng.randn(C, n_max, F).astype(np.float32)
    y = rng.randint(0, 10, (C, n_max)).astype(np.int32)
    # heterogeneous sizes incl. a short (padded, wrapping) client and
    # an EMPTY one (the inert padding-client edge: row plans for
    # size 0 degenerate to row 0)
    sizes = np.asarray([12, 5, 1, 0, 7], np.int32)
    return HostClientStore(ClientData(x=x, y=y, sizes=sizes))


def _force_numpy_fallback(monkeypatch):
    import fedtorch_tpu.native.host_pipeline as hp
    monkeypatch.setattr(hp, "_lib", None)
    monkeypatch.setattr(hp, "_lib_tried", True)


@pytest.mark.parametrize("order", ["fwd", "rev"])
def test_feed_packer_native_equals_numpy(monkeypatch, order):
    """The packed feed must be bitwise-identical whether the native
    ft_gather_rows or the numpy fallback gathers it — both client
    orders, wrapped short clients, and the empty-client edge — so CI
    on toolchain-less hosts still pins the streaming contract."""
    from fedtorch_tpu.native import native_available
    store = _toy_store()
    idx = np.asarray([3, 1, 0, 2], np.int64)
    if order == "rev":
        idx = idx[::-1].copy()
    rng = np.random.RandomState(1)
    rows = rng.randint(0, store.n_max, (4, 7)).astype(np.int64)
    rows[np.where(idx == 3)[0][0]] = 0  # empty client: plan is row 0

    numpy_ref = RoundFeed(
        idx=idx.astype(np.int32), sizes=store.sizes[idx],
        x=store.x[idx[:, None], rows], y=store.y[idx[:, None], rows],
        pre_x=store.x[idx[:, None], np.arange(2)[None, :]],
        pre_y=store.y[idx[:, None], np.arange(2)[None, :]])

    if native_available():
        native_feed = store.pack(idx, rows, batch_size=2)
        assert_trees_equal(tuple(native_feed), tuple(numpy_ref))
    _force_numpy_fallback(monkeypatch)
    fallback_feed = store.pack(idx, rows, batch_size=2)
    assert_trees_equal(tuple(fallback_feed), tuple(numpy_ref))


def test_feed_nbytes_counts_all_leaves():
    store = _toy_store()
    feed = store.pack(np.asarray([0, 1]), np.zeros((2, 4), np.int64), 2)
    expected = sum(np.asarray(leaf).nbytes for leaf in feed
                   if leaf is not None)  # probe leaves unused here
    assert feed_nbytes(feed) == expected


def test_pre_rows_clamp_when_batch_exceeds_shard():
    """batch_size > n_max: the hook batch must repeat the LAST row —
    the device plane's jnp out-of-bounds gather clamps — instead of
    walking the flat view into the next client's shard (or off the
    end of the store for the last client)."""
    import jax.numpy as jnp
    store = _toy_store()  # n_max = 12
    idx = np.asarray([1, 4])  # 4 is the LAST client: overflow would
    #                           index past the end of the flat view
    rows = np.zeros((2, 3), np.int64)
    feed = store.pack(idx, rows, batch_size=15)
    device_ref = np.asarray(
        jnp.asarray(store.x)[idx[:, None], jnp.arange(15)[None, :]])
    np.testing.assert_array_equal(feed.pre_x, device_ref)


# -- supervisor interplay ----------------------------------------------------
def test_supervisor_rollback_resyncs_stream(monkeypatch):
    """A supervised unhealthy round rolls back AND reseeds — both
    rewrite the (rng, round) pair the host producer replays from. The
    rollback path must invalidate the stream so the retry re-syncs
    instead of consuming stale feeds (which would raise a desync
    error or silently feed wrong rows)."""
    from fedtorch_tpu.robustness import RoundSupervisor
    t = build("stream")
    sup = RoundSupervisor(t, sleep_fn=lambda s: None)
    fail_once = {"armed": True}
    orig = RoundSupervisor._healthy

    def flaky(self, health):
        if fail_once["armed"]:
            fail_once["armed"] = False
            return False
        return orig(self, health)

    monkeypatch.setattr(RoundSupervisor, "_healthy", flaky)
    server, clients = t.init_state(jax.random.key(0))
    for _ in range(3):
        server, clients, _ = sup.run_round(server, clients)
    assert sup.stats.rollbacks == 1
    assert sup.stats.rounds == 3
    assert int(jax.device_get(server.round)) == 3
    t.invalidate_stream()


# -- gates / config / CLI ----------------------------------------------------
def test_run_rounds_scans_on_stream_plane():
    """The scanned streamed program (parallel/round_program.py): the
    stream plane serves run_rounds — the producer packs an [R, ...]
    feed window — and the trajectory matches per-round device rounds
    BITWISE. Construction must NOT pre-refuse the scan cell (the gate,
    when one applies, fires at the run_rounds call — satellite of
    ISSUE 11); mixed dispatch granularity re-syncs the producer."""
    t_dev = build("device")
    t_str = build("stream")
    s1, c1 = t_dev.init_state(jax.random.key(5))
    s2, c2 = t_str.init_state(jax.random.key(5))
    for _ in range(4):
        s1, c1, m1 = t_dev.run_round(s1, c1)
    # per-round then scanned: the granularity switch re-syncs the
    # producer from live device state (window 1 -> window 3)
    s2, c2, _ = t_str.run_round(s2, c2)
    s2, c2, ms = t_str.run_rounds(s2, c2, 3)
    assert_trees_equal((s1.params, s1.aux, c1), (s2.params, s2.aux, c2))
    # stacked metrics: the last scanned round's row equals the device
    # plane's final per-round metrics
    assert_trees_equal(jax.tree.map(lambda a: a[-1], ms), m1)
    t_str.invalidate_stream()


def test_explicit_shard_gather_streams_full_shards():
    """Explicit gather_mode='shard' on the stream plane is a FEED
    LAYOUT now (ISSUE 18 gate lift): the producer packs whole
    [k, n_max, ...] client shards and the trajectory matches the
    device shard program bitwise (same epoch_permutation row order)."""
    cfg_d = make_cfg("device")
    cfg_s = make_cfg("stream")
    data = build_federated_data(cfg_d)
    model = define_model(cfg_d, batch_size=cfg_d.data.batch_size)
    t_dev = FederatedTrainer(cfg_d, model, make_algorithm(cfg_d),
                             data.train, gather_mode="shard")
    t_str = FederatedTrainer(cfg_s, define_model(
        cfg_s, batch_size=cfg_s.data.batch_size),
        make_algorithm(cfg_s), data.train, gather_mode="shard")
    assert t_str.gather_mode == "shard"
    s1, c1 = t_dev.init_state(jax.random.key(2))
    s2, c2 = t_str.init_state(jax.random.key(2))
    for _ in range(2):
        s1, c1, m1 = t_dev.run_round(s1, c1)
        s2, c2, m2 = t_str.run_round(s2, c2)
    assert_trees_equal((s1.params, s1.aux, c1), (s2.params, s2.aux, c2))
    assert_trees_equal(m1, m2)
    t_str.invalidate_stream()


@pytest.mark.parametrize("algorithm,kw", [
    # lifted gates (ISSUE 18): qFFL's full-shard loss streams via the
    # 'shard' feed layout; default-uniform DRFA's dual phase streams
    # via the host probe plan — both must match the device plane
    # BITWISE (DRFA: including the lambda trajectory in server aux)
    ("qffl", {"qffl_q": 1.0}),
    ("fedavg", {"drfa": True}),
])
def test_lifted_algorithms_stream_bitwise(algorithm, kw):
    t_dev = build("device", algorithm=algorithm, **kw)
    t_str = build("stream", algorithm=algorithm, **kw)
    s1, c1 = t_dev.init_state(jax.random.key(4))
    s2, c2 = t_str.init_state(jax.random.key(4))
    with RecompilationSentinel() as sentinel:
        for _ in range(3):
            s1, c1, m1 = t_dev.run_round(s1, c1)
            s2, c2, m2 = t_str.run_round(s2, c2)
        jax.block_until_ready(s2.params)
    assert_trees_equal((s1.params, s1.aux, c1), (s2.params, s2.aux, c2))
    assert_trees_equal(m1, m2)
    # trace-once holds for the lifted algorithms' streamed programs
    sentinel.assert_traces(t_str.stream_trace_name, expected=1)
    t_str.invalidate_stream()


def test_drfa_lambda_sampling_still_refused_on_stream():
    """The remaining DRFA feed refusal: the lambda-DISTRIBUTED draw
    reads device state (the dual variable) the host feed builder
    cannot see."""
    cfg = make_cfg("stream", drfa=True, drfa_lambda_sampling=True)
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    with pytest.raises(ValueError, match="participation"):
        FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)


def test_personal_val_split_raises():
    cfg = make_cfg("stream", algorithm="apfl")
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    with pytest.raises(ValueError, match="validation"):
        FederatedTrainer(cfg, model, make_algorithm(cfg), data.train,
                         val_data=data.val)


def test_config_rejects_unknown_plane():
    with pytest.raises(ValueError, match="data_plane"):
        ExperimentConfig(
            data=DataConfig(data_plane="rows")).finalize()


def test_cli_flag_maps():
    from fedtorch_tpu.cli import args_to_config, build_parser
    args = build_parser().parse_args(
        ["--federated", "true", "-d", "synthetic",
         "--data_plane", "stream"])
    assert args_to_config(args).data.data_plane == "stream"
    assert dataclasses.asdict(
        args_to_config(build_parser().parse_args(
            ["--federated", "true", "-d", "synthetic"]))
    )["data"]["data_plane"] == "device"
