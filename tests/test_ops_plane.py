"""Operations plane (ISSUE 15, docs/observability.md "Operating and
comparing runs"): run registry, regression-gated compare, live watch,
and round-wall critical-path attribution.

The contracts made executable here:

* ``watch``/``compare``/``runs`` NEVER import jax (subprocess-pinned,
  like the ``report`` rule they inherit);
* every JSONL reader is torn-tail tolerant with a COUNTED warning, and
  elastic-restart-appended files stitch unambiguously via the
  per-writer ``seq`` stamp (last write per round wins);
* ``overlap_efficiency`` math: hidden producer wall over producer
  wall, clamped, ``None`` for an idle producer or a reset counter;
* ``compare --gate`` exits 1 on the seeded synthetic regression
  fixture, 0 on self-compare, 2 on unusable input — exact codes;
* the end-to-end slow-lane smoke: two real CLI runs through the gate,
  and a stream-plane run emits ``overlap_efficiency`` on its rows.
"""
import json
import os
import shutil
import subprocess
import sys

import pytest

from fedtorch_tpu.telemetry.critical_path import (
    StreamOverlapTracker, device_floor_s, overlap_efficiency,
    overlap_summary, replay_overlap, round_wall_decomposition,
)
from fedtorch_tpu.telemetry.schema import (
    METRICS_OPTIONAL, count_restarts, load_jsonl, stitch_rows,
    validate_metrics_row,
)

FIXROOT = os.path.join(os.path.dirname(__file__), "data", "ops_runs")
CLEAN = os.path.join(FIXROOT, "clean")
TORN = os.path.join(FIXROOT, "torn")
RESTART = os.path.join(FIXROOT, "restart")
REGRESSED = os.path.join(FIXROOT, "regressed")
GATES = os.path.join(FIXROOT, "gates.json")


# -- overlap_efficiency math --------------------------------------------


class TestOverlapEfficiency:
    def test_fully_hidden(self):
        assert overlap_efficiency(1.0, 0.5, 0.0) == 1.0

    def test_nothing_hidden(self):
        # consumer waited the whole producer wall (and then some —
        # extra wait clamps at 0, nothing provably hid)
        assert overlap_efficiency(1.0, 0.0, 1.0) == 0.0
        assert overlap_efficiency(1.0, 0.0, 5.0) == 0.0

    def test_partial(self):
        assert overlap_efficiency(1.0, 1.0, 0.5) == pytest.approx(0.75)

    def test_idle_producer_is_none_not_perfect(self):
        assert overlap_efficiency(0.0, 0.0, 0.0) is None
        assert overlap_efficiency(0.0, 0.0, 1.0) is None

    def test_negative_wait_clamped(self):
        assert overlap_efficiency(1.0, 0.0, -3.0) == 1.0

    def test_tracker_deltas(self):
        t = StreamOverlapTracker()
        assert t.observe({"stream_gather_s": 1.0, "stream_h2d_s": 0.5,
                          "stream_wait_s": 0.1}) is None  # first row
        eff = t.observe({"stream_gather_s": 2.0, "stream_h2d_s": 1.0,
                         "stream_wait_s": 0.4})
        # deltas: gather 1.0, h2d 0.5, wait 0.3 -> 1 - 0.3/1.5
        assert eff == pytest.approx(0.8)

    def test_tracker_counter_reset_yields_none(self):
        t = StreamOverlapTracker()
        t.observe({"stream_gather_s": 5.0, "stream_h2d_s": 1.0,
                   "stream_wait_s": 1.0})
        # producer rebuilt: cumulative counters re-zeroed
        assert t.observe({"stream_gather_s": 0.5, "stream_h2d_s": 0.1,
                          "stream_wait_s": 0.0}) is None
        # and the NEXT delta is attributable again
        assert t.observe({"stream_gather_s": 1.5, "stream_h2d_s": 0.1,
                          "stream_wait_s": 0.0}) == 1.0

    def test_tracker_ignores_non_stream_rows(self):
        t = StreamOverlapTracker()
        assert t.observe({"round": 0, "loss": 1.0}) is None

    def test_replay_prefers_emitted_gauge(self):
        rows = [
            {"stream_gather_s": 1.0, "stream_h2d_s": 0.0,
             "stream_wait_s": 0.0},
            {"stream_gather_s": 2.0, "stream_h2d_s": 0.0,
             "stream_wait_s": 0.5, "overlap_efficiency": 0.123},
        ]
        assert replay_overlap(rows) == [None, 0.123]

    def test_counter_total_is_reset_aware(self):
        from fedtorch_tpu.telemetry.critical_path import _counter_total
        rows = [{"c": 1.0}, {"c": 3.0}, {"c": 0.5}, {"c": 2.5}]
        # segment 1 grew to 3.0, the restarted segment grew to 2.5
        assert _counter_total(rows, "c") == pytest.approx(5.5)
        assert _counter_total(rows, "missing") == 0.0

    def test_overlap_summary_spans_restart_reset(self):
        def row(g, h, w):
            return {"stream_gather_s": g, "stream_h2d_s": h,
                    "stream_wait_s": w}
        rows = [row(1.0, 0.5, 0.1), row(2.0, 1.0, 0.2),
                # elastic restart: counters re-zeroed
                row(0.5, 0.25, 0.05), row(1.5, 0.75, 0.15)]
        ov = overlap_summary(rows)
        # producer wall = (2.0+1.0) + (1.5+0.75); wait = 0.2 + 0.15 —
        # NOT the last row's cumulative values alone
        assert ov["producer_wall_s"] == pytest.approx(5.25)
        assert ov["consumer_wait_s"] == pytest.approx(0.35)

    def test_decomposition_exposure_spans_restart_reset(self):
        rows = [{"round": r, "round_s": 0.1, "stream_wait_s": w}
                for r, w in enumerate([0.1, 0.2, 0.05, 0.15])]
        dec = round_wall_decomposition(rows)
        # growth: 0.1 (r1) + 0.05 (restart segment r2) + 0.1 (r3)
        # over 3 intervals — the restart must not clamp it to ~0
        assert dec["stream_exposed_s"] == pytest.approx(0.25 / 3)

    def test_overlap_summary_on_fixture(self):
        _meta, rows, _torn = _load_fixture_rows(CLEAN)
        ov = overlap_summary(rows)
        assert ov["rounds"] == 5
        assert ov["mean"] == pytest.approx(0.9667, abs=1e-4)
        assert 0.0 < ov["exposed_frac"] < 1.0


def _load_fixture_rows(run_dir):
    header, records, torn = load_jsonl(
        os.path.join(run_dir, "metrics.jsonl"))
    return (header or {}).get("run", {}), stitch_rows(records), torn


# -- torn tails + restart stitching -------------------------------------


class TestTornAndStitch:
    def test_clean_has_no_torn_lines(self):
        _m, rows, torn = _load_fixture_rows(CLEAN)
        assert torn == 0 and len(rows) == 6

    def test_torn_tail_counted_not_fatal(self):
        _m, rows, torn = _load_fixture_rows(TORN)
        assert torn == 1
        assert len(rows) == 5  # the torn final row is lost, counted

    def test_restart_stitches_and_counts(self):
        header, records, torn = load_jsonl(
            os.path.join(RESTART, "metrics.jsonl"))
        assert torn == 1  # the crash's buried partial line
        assert count_restarts(records) == 1  # seq dropped once
        rows = stitch_rows(records)
        assert [r["round"] for r in rows] == [0, 1, 2, 3, 4, 5]
        # the re-run rounds superseded the pre-crash ones (last write
        # wins): the restart leg wrote loss - 0.001
        assert rows[2]["loss"] == pytest.approx(1.0 - 0.001)

    def test_restart_after_single_row_counts(self):
        # pre-crash writer flushed exactly one row (seq 0); restart's
        # first row is seq 0 again — a repeat IS a boundary
        assert count_restarts([{"seq": 0}, {"seq": 0},
                               {"seq": 1}]) == 1
        assert count_restarts([{"seq": 0}, {"seq": 1}]) == 0
        assert count_restarts([{}, {"seq": 0}]) == 0

    def test_every_fixture_row_validates(self):
        for d in (CLEAN, RESTART, REGRESSED):
            _m, rows, _t = _load_fixture_rows(d)
            for row in rows:
                validate_metrics_row(row)

    def test_report_counts_torn_and_restarts(self):
        from fedtorch_tpu.tools.report import render, summarize
        s = summarize(RESTART)
        assert s["torn_lines"] == 1 and s["restarts"] == 1
        out = render(RESTART)
        assert "1 torn JSONL line(s)" in out
        assert "restart" in out


# -- critical-path decomposition ----------------------------------------


class TestDecomposition:
    def test_device_floor_from_costs_doc(self):
        with open(os.path.join(CLEAN, "program_costs.json")) as f:
            doc = json.load(f)
        # 4.9e11 FLOPs at 98 TF/chip x 1 chip = 5 ms
        assert device_floor_s(doc) == pytest.approx(0.005)
        assert device_floor_s(None) is None
        assert device_floor_s({"programs": {}, "primary": "x"}) is None

    def test_decomposition_on_fixture(self):
        with open(os.path.join(CLEAN, "program_costs.json")) as f:
            doc = json.load(f)
        _m, rows, _t = _load_fixture_rows(CLEAN)
        dec = round_wall_decomposition(rows, doc)
        assert dec["rounds"] == 5  # compile round excluded
        assert dec["round_s_mean"] == pytest.approx(0.1)
        assert dec["device_floor_frac"] == pytest.approx(0.05)
        assert dec["host_frac"] == pytest.approx(0.95)
        assert dec["unattributed_s"] == pytest.approx(0.095)

    def test_report_renders_critical_path(self):
        from fedtorch_tpu.tools.report import render, summarize
        s = summarize(CLEAN)
        assert s["critical_path"]["host_frac"] == pytest.approx(0.95)
        assert s["overlap"]["mean"] == pytest.approx(0.9667, abs=1e-4)
        out = render(CLEAN)
        assert "critical path" in out and "device floor" in out
        assert "stream overlap" in out

    def test_new_gauges_cataloged(self):
        for field in ("overlap_efficiency", "round_device_min_s",
                      "round_host_frac", "seq", "t"):
            assert field in METRICS_OPTIONAL


class TestAnomalyReplay:
    def test_replay_tolerates_torn_tail(self):
        from fedtorch_tpu.telemetry.anomaly import replay_anomalies
        out = replay_anomalies(TORN, zscore=6.0)
        assert out["torn_lines"] == 1 and out["rows"] == 5
        assert isinstance(out["anomalies"], list)
        assert out["summary"]["loss"]["observations"] == 5

    def test_replay_flags_seeded_excursion(self, tmp_path):
        from fedtorch_tpu.telemetry.anomaly import replay_anomalies
        d = str(tmp_path / "run")
        os.makedirs(d)
        with open(os.path.join(d, "metrics.jsonl"), "w") as f:
            f.write(json.dumps({"schema": "fedtorch_tpu.metrics/v1"})
                    + "\n")
            for r in range(14):
                loss = 1.0 + 0.001 * (r % 3) if r < 13 else 50.0
                f.write(json.dumps({"round": r, "loss": loss}) + "\n")
        out = replay_anomalies(d, zscore=6.0, warmup=5)
        assert any(a["field"] == "loss" and a["round"] == 13
                   for a in out["anomalies"])


# -- seq/t stamping ------------------------------------------------------


class TestRowStamps:
    def test_writer_stamps_seq_and_t(self, tmp_path):
        from fedtorch_tpu.telemetry.metrics import JsonlWriter
        from fedtorch_tpu.telemetry.schema import METRICS_SCHEMA
        path = str(tmp_path / "metrics.jsonl")
        w = JsonlWriter(path, METRICS_SCHEMA)
        base = {"round": 0, "round_s": 0.1, "loss": 1.0, "acc": 0.5,
                "lr": 0.1, "n_online": 2.0, "comm_bytes": 10.0}
        for r in range(3):
            w.write(dict(base, round=r))
        w.close()
        _h, rows, torn = load_jsonl(path)
        assert torn == 0
        assert [r["seq"] for r in rows] == [0, 1, 2]
        for r in rows:
            assert isinstance(r["t"], float)
            validate_metrics_row(r)

    def test_existing_t_not_overwritten(self, tmp_path):
        from fedtorch_tpu.telemetry.metrics import JsonlWriter
        from fedtorch_tpu.telemetry.schema import EVENTS_SCHEMA
        path = str(tmp_path / "events.jsonl")
        w = JsonlWriter(path, EVENTS_SCHEMA)
        w.write({"t": 123.0, "event": "run.start"}, flush=True)
        w.close()
        _h, rows, _torn = load_jsonl(path)
        assert rows[0]["t"] == 123.0 and rows[0]["seq"] == 0

    def test_restart_writer_isolates_torn_tail(self, tmp_path):
        """A restart writer appending to a file whose last line was
        torn mid-append (no newline) must NOT merge its first row into
        the torn bytes — both rows would be lost and the STALE
        pre-crash row would win the stitch."""
        from fedtorch_tpu.telemetry.metrics import JsonlWriter
        from fedtorch_tpu.telemetry.schema import METRICS_SCHEMA
        path = str(tmp_path / "metrics.jsonl")
        w = JsonlWriter(path, METRICS_SCHEMA)
        w.write({"round": 0}, flush=True)
        w.write({"round": 1}, flush=True)
        w.close()
        # crash: tear the final line mid-append (strip its newline too)
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[:-10])
        # elastic restart: a fresh writer appends re-run rounds
        w2 = JsonlWriter(path, METRICS_SCHEMA)
        w2.write({"round": 1}, flush=True)
        w2.close()
        header, records, torn = load_jsonl(path)
        assert torn == 1  # the torn bytes alone, isolated
        rows = stitch_rows(records)
        assert [r["round"] for r in rows] == [0, 1]
        # the restart's round-1 row won (seq restarted at 0)
        assert rows[1]["seq"] == 0
        assert count_restarts(records) == 1

    def test_caller_row_not_mutated(self, tmp_path):
        from fedtorch_tpu.telemetry.metrics import JsonlWriter
        from fedtorch_tpu.telemetry.schema import METRICS_SCHEMA
        w = JsonlWriter(str(tmp_path / "m.jsonl"), METRICS_SCHEMA)
        row = {"round": 0}
        w.write(row)
        w.close()
        assert row == {"round": 0}


# -- compare + gates -----------------------------------------------------


class TestCompareGates:
    def test_self_compare_exits_zero(self, capsys):
        from fedtorch_tpu.tools.compare import main
        assert main([CLEAN, CLEAN, "--gate", GATES]) == 0
        out = capsys.readouterr().out
        assert "FAIL" not in out

    def test_seeded_regression_exits_one(self, capsys):
        from fedtorch_tpu.tools.compare import main
        assert main([CLEAN, REGRESSED, "--gate", GATES]) == 1
        out = capsys.readouterr().out
        assert "GATE FAIL" in out
        # the seeded regressions each trip their gate
        assert "rounds_per_s_steady" in out
        assert "final_acc" in out
        assert "overlap_efficiency_mean" in out
        assert "pc.peak_hbm_bytes" in out

    def test_no_gate_is_informational_zero(self):
        from fedtorch_tpu.tools.compare import main
        assert main([CLEAN, REGRESSED]) == 0

    def test_missing_run_dir_exits_two(self, tmp_path):
        from fedtorch_tpu.tools.compare import main
        assert main([str(tmp_path / "nope"), CLEAN]) == 2

    def test_bad_gate_file_exits_two(self, tmp_path):
        from fedtorch_tpu.tools.compare import main
        bad = tmp_path / "bad_gates.json"
        bad.write_text(json.dumps({
            "schema": "fedtorch_tpu.compare_gates/v1",
            "gates": {"final_acc": {"max_decreese_abs": 0.1}}}))
        assert main([CLEAN, CLEAN, "--gate", str(bad)]) == 2

    def test_gate_limits_must_be_numeric(self):
        from fedtorch_tpu.tools.compare import GATES_SCHEMA, load_gates
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump({"schema": GATES_SCHEMA,
                       "gates": {"x": {"max_b": True}}}, f)
        with pytest.raises(ValueError, match="must be a number"):
            load_gates(f.name)

    def test_required_gate_fails_on_missing_metric(self):
        from fedtorch_tpu.tools.compare import (
            compare_runs, evaluate_gates,
        )
        cmp_doc = compare_runs(CLEAN, CLEAN)
        gates = {"gates": {
            "gauge.no_such_gauge": {"min_b": 1.0, "required": True},
            "gauge.also_missing": {"min_b": 1.0}}}
        failures, checked, skipped = evaluate_gates(cmp_doc, gates)
        assert [f["metric"] for f in failures] == ["gauge.no_such_gauge"]
        assert skipped == ["gauge.also_missing"]

    def test_compare_doc_contents(self):
        from fedtorch_tpu.tools.compare import compare_runs
        doc = compare_runs(CLEAN, REGRESSED)
        m = doc["metrics"]
        assert m["rounds_per_s_steady"]["frac"] == \
            pytest.approx(-1 / 3, abs=1e-3)
        assert m["pc.peak_hbm_bytes"]["delta"] == pytest.approx(1e8)
        assert doc["trajectory"]["rounds_compared"] == 6
        assert doc["trajectory"]["acc_max_abs_gap"] == \
            pytest.approx(0.1)
        assert doc["events"]["anomaly.detected"]["delta"] == 1

    def test_unwritable_out_exits_two(self, tmp_path):
        from fedtorch_tpu.tools.compare import main
        assert main([CLEAN, CLEAN,
                     "--out", str(tmp_path / "no" / "dir" / "o.json")
                     ]) == 2

    def test_unreadable_run_dir_exits_two(self, tmp_path, monkeypatch):
        """PermissionError (and any other OSError) is 'unusable
        input' (2), never a fake gated regression (1)."""
        from fedtorch_tpu.tools import compare as cmp_mod

        def boom(_dir):
            raise PermissionError("metrics.jsonl: permission denied")
        monkeypatch.setattr(cmp_mod, "_summary", boom)
        assert cmp_mod.main([CLEAN, CLEAN]) == 2

    def test_out_file_written(self, tmp_path):
        from fedtorch_tpu.tools.compare import main
        out = tmp_path / "cmp.json"
        assert main([CLEAN, CLEAN, "--out", str(out)]) == 0
        doc = json.loads(out.read_text())
        assert doc["schema"] == "fedtorch_tpu.run_compare/v1"

    def test_cli_routing(self, capsys):
        from fedtorch_tpu.cli import main
        assert main(["compare", CLEAN, CLEAN]) == 0
        assert "compare:" in capsys.readouterr().out


# -- the runs registry ---------------------------------------------------


class TestRunsRegistry:
    def test_index_document(self, tmp_path):
        root = str(tmp_path / "root")
        shutil.copytree(FIXROOT, root)
        from fedtorch_tpu.telemetry.runs import build_index, load_index
        doc = build_index(root)
        assert doc["schema"] == "fedtorch_tpu.runs_index/v1"
        names = {r["name"] for r in doc["runs"]}
        assert names == {"clean", "torn", "restart", "regressed"}
        by = {r["name"]: r for r in doc["runs"]}
        assert by["clean"]["health"]["intent"] == "complete"
        assert by["torn"]["torn_lines"] == 1
        assert by["restart"]["restarts"] == 1
        assert by["regressed"]["anomalies"] == 1
        assert by["clean"]["overlap_efficiency_mean"] == \
            pytest.approx(0.9667, abs=1e-4)
        assert by["clean"]["program_costs"]["primary"] == "round_stream"
        # written atomically and loadable
        assert load_index(root)["runs"]

    def test_broken_dir_becomes_error_record(self, tmp_path):
        root = tmp_path / "root"
        run = root / "broken"
        run.mkdir(parents=True)
        (run / "metrics.jsonl").write_text("")  # empty: no header, no rows
        (run / "health.json").write_text("{not json")
        from fedtorch_tpu.telemetry.runs import build_index
        doc = build_index(str(root), write=False)
        # unreadable health degrades to None, empty metrics to 0 rounds
        # — neither kills the index
        assert len(doc["runs"]) == 1
        rec = doc["runs"][0]
        assert rec["name"] == "broken" and rec.get("rounds", 0) == 0

    def test_filters(self):
        from fedtorch_tpu.telemetry.runs import match_filters
        rec = {"meta": {"algorithm": "fedavg"}, "rounds": 6,
               "health": {"intent": "complete"}}
        assert match_filters(rec, ["meta.algorithm=fed"])
        assert match_filters(rec, ["rounds=6",
                                   "health.intent=complete"])
        assert not match_filters(rec, ["rounds=7"])
        assert not match_filters(rec, ["meta.no_such_key=x"])

    def test_cli_routing_and_filter(self, tmp_path, capsys):
        root = str(tmp_path / "root")
        shutil.copytree(FIXROOT, root)
        from fedtorch_tpu.cli import main
        assert main(["runs", root, "--filter",
                     "health.intent=error", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert [r["name"] for r in doc["runs"]] == ["torn"]

    def test_not_a_directory_exits_two(self, tmp_path):
        from fedtorch_tpu.telemetry.runs import main
        assert main([str(tmp_path / "nope")]) == 2


# -- watch ---------------------------------------------------------------


class TestWatch:
    def _copy(self, src, tmp_path):
        dst = str(tmp_path / os.path.basename(src))
        shutil.copytree(src, dst)
        return dst

    def test_tail_incremental_with_partial_line(self, tmp_path):
        from fedtorch_tpu.tools.watch import JsonlTail
        path = str(tmp_path / "m.jsonl")
        tail = JsonlTail(path)
        assert tail.poll() == []  # not written yet
        with open(path, "w") as f:
            f.write('{"round": 0}\n{"round": 1, "lo')
            f.flush()
        recs = tail.poll()
        assert [r["round"] for r in recs] == [0]
        assert tail.pending_partial and tail.torn == 0
        # the writer finishes the line: it parses on the next poll
        with open(path, "a") as f:
            f.write('ss": 1.0}\n')
        recs = tail.poll()
        assert recs == [{"round": 1, "loss": 1.0}]
        assert not tail.pending_partial

    def test_tail_counts_durably_torn_line(self, tmp_path):
        from fedtorch_tpu.tools.watch import JsonlTail
        path = str(tmp_path / "m.jsonl")
        with open(path, "w") as f:
            f.write('{"round": 0}\n{"torn\n{"round": 1}\n')
        tail = JsonlTail(path)
        recs = tail.poll()
        assert [r["round"] for r in recs] == [0, 1]
        assert tail.torn == 1

    def test_tail_survives_truncation(self, tmp_path):
        from fedtorch_tpu.tools.watch import JsonlTail
        path = str(tmp_path / "m.jsonl")
        with open(path, "w") as f:
            f.write('{"round": 0}\n{"round": 1}\n')
        tail = JsonlTail(path)
        assert len(tail.poll()) == 2
        # atomic-replace style rotation: smaller file, fresh content
        with open(path, "w") as f:
            f.write('{"round": 9}\n')
        assert tail.poll() == [{"round": 9}]

    def test_state_counts_restarts_and_renders(self, tmp_path):
        from fedtorch_tpu.tools.watch import WatchState, render_watch
        from fedtorch_tpu.telemetry.health import read_health
        d = self._copy(RESTART, tmp_path)
        state = WatchState(d)
        state.poll()
        assert state.restarts == 1 and state.torn == 1
        assert [r["round"] for r in state.rows()] == [0, 1, 2, 3, 4, 5]
        out = render_watch(state, read_health(d), now=1754300200.0)
        assert "intent=complete" in out
        assert "rounds: 6/6" in out
        assert "overlap_eff=0.97" in out
        assert "torn=1" in out and "restarts=1" in out
        assert "loss:" in out and "acc:" in out

    def test_rate_falls_back_to_walls_across_restart(self, tmp_path):
        """A window straddling a restart boundary must not divide by
        the wall-clock span (it contains the outage downtime) — the
        rate falls back to the round_s walls."""
        from fedtorch_tpu.tools.watch import WatchState
        d = str(tmp_path / "live")
        os.makedirs(d)
        with open(os.path.join(d, "metrics.jsonl"), "w") as f:
            for i, (seq, t) in enumerate(
                    [(0, 100.0), (1, 100.1),
                     (0, 700.0), (1, 700.1)]):  # 10-min outage gap
                f.write(json.dumps({"round": i if seq else i,
                                    "seq": seq, "t": t,
                                    "round_s": 0.1}) + "\n")
        state = WatchState(d)
        state.poll()
        # walls: 4 rounds x 0.1 s -> 10 rounds/s, NOT 3/600.2
        assert state.rate_rounds_per_s() == pytest.approx(10.0)

    def test_tracker_baseline_advances_under_emitted_gauges(
            self, tmp_path):
        """The state must feed its tracker EVERY row (preferring the
        emitted gauge): an idle-producer round after a string of
        gauge-carrying rows must not fabricate a multi-round
        efficiency from a stale baseline."""
        from fedtorch_tpu.tools.watch import WatchState
        d = str(tmp_path / "live")
        os.makedirs(d)
        mpath = os.path.join(d, "metrics.jsonl")
        rows = [
            {"round": 0, "stream_gather_s": 1.0, "stream_h2d_s": 0.0,
             "stream_wait_s": 0.0},
            {"round": 1, "stream_gather_s": 2.0, "stream_h2d_s": 0.0,
             "stream_wait_s": 1.0, "overlap_efficiency": 0.9},
            # idle producer round: counters unchanged, no gauge —
            # derived efficiency is None, display keeps the last one
            {"round": 2, "stream_gather_s": 2.0, "stream_h2d_s": 0.0,
             "stream_wait_s": 1.0},
        ]
        with open(mpath, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        state = WatchState(d)
        state.poll()
        assert state.overlap_last == pytest.approx(0.9)

    def test_snapshot_mode_exit_codes(self, tmp_path, capsys):
        from fedtorch_tpu.cli import main
        d = self._copy(CLEAN, tmp_path)
        assert main(["watch", d, "--once"]) == 0
        out = capsys.readouterr().out
        assert "watch:" in out and "rate=" in out
        assert main(["watch", str(tmp_path / "nope")]) == 2

    def test_live_loop_incremental(self, tmp_path):
        """Simulated live run: rows appended between polls, health
        atomically replaced — the state follows without re-reading
        from scratch (offsets advance monotonically)."""
        from fedtorch_tpu.tools.watch import WatchState
        d = str(tmp_path / "live")
        os.makedirs(d)
        mpath = os.path.join(d, "metrics.jsonl")
        with open(mpath, "w") as f:
            f.write(json.dumps({"schema": "fedtorch_tpu.metrics/v1",
                                "run": {"num_comms": 4}}) + "\n")
        state = WatchState(d)
        state.poll()
        assert state.meta["num_comms"] == 4 and not state.rows()
        for r in range(4):
            with open(mpath, "a") as f:
                f.write(json.dumps({"round": r, "round_s": 0.1,
                                    "loss": 1.0, "acc": 0.5,
                                    "lr": 0.1, "n_online": 2.0,
                                    "comm_bytes": 1.0, "seq": r,
                                    "t": 100.0 + r}) + "\n")
            state.poll()
            assert len(state.rows()) == r + 1


# -- the no-jax rule -----------------------------------------------------


class TestNoJaxImport:
    def test_ops_tools_never_import_jax(self):
        """watch/compare/runs (and the report they build on) parse a
        run dir end-to-end in a subprocess without jax ever landing
        in sys.modules — the monitor-box rule."""
        code = (
            "import sys\n"
            "from fedtorch_tpu.tools.compare import main as cmain\n"
            "from fedtorch_tpu.tools.watch import main as wmain\n"
            "from fedtorch_tpu.telemetry.runs import main as rmain\n"
            f"assert cmain([{CLEAN!r}, {REGRESSED!r}]) == 0\n"
            f"assert wmain([{CLEAN!r}, '--once']) == 0\n"
            f"assert rmain([{FIXROOT!r}, '--no-write']) == 0\n"
            "assert 'jax' not in sys.modules, 'jax was imported'\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr


# -- end-to-end slow-lane smoke ------------------------------------------


def _mini_cfg(run_dir, plane="stream", seed=6):
    from fedtorch_tpu.config import (
        CheckpointConfig, DataConfig, ExperimentConfig,
        FederatedConfig, ModelConfig, OptimConfig, TrainConfig,
    )
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=10,
                        batch_size=8, data_plane=plane),
        federated=FederatedConfig(
            federated=True, num_clients=8, num_comms=4,
            online_client_rate=0.5, algorithm="fedavg",
            sync_type="local_step"),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.1, weight_decay=0.0),
        train=TrainConfig(local_step=2, manual_seed=seed, eval_freq=4),
        checkpoint=CheckpointConfig(run_dir=run_dir, debug=False),
    ).finalize()


class TestEndToEndGateSmoke:
    def test_stream_run_emits_overlap_and_self_compare_gates(
            self, tmp_path):
        """The gate smoke (slow lane): a real stream-plane CLI run
        emits per-round overlap_efficiency, indexes into the
        registry, and self-compares clean through the gate file."""
        from fedtorch_tpu.cli import main, run_experiment
        run_dir = str(tmp_path / "runs_root" / "stream_run")
        run_experiment(_mini_cfg(run_dir))
        _m, rows, torn = _load_fixture_rows(run_dir)
        assert torn == 0 and len(rows) == 4
        # acceptance: overlap_efficiency is emitted on stream-plane
        # runs (round 0 has no prior producer baseline)
        assert any("overlap_efficiency" in r for r in rows[1:])
        for r in rows:
            validate_metrics_row(r)
            assert r["seq"] == r["round"]
        assert main(["runs", str(tmp_path / "runs_root"),
                     "--no-write"]) == 0
        assert main(["compare", run_dir, run_dir,
                     "--gate", GATES]) == 0
