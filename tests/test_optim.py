"""Dual-mode optimizer parity tests.

The reference SGD (optimizers/sgd.py:67-129) is exercised directly (torch
cpu) on the same small problems and must agree with the functional JAX
rebuild step for step — local steps (apply_lr=True) and server steps
(apply_lr=False, scale=s, out-momentum).
"""
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.config import OptimConfig
from fedtorch_tpu.core import optim as fopt

sys.path.insert(0, "/root/reference")


def _torch_sgd(params_np, cfg: OptimConfig):
    import torch
    pytest.importorskip(
        "fedtorch",
        reason="reference checkout not mounted at /root/reference")
    from fedtorch.components.optimizers.sgd import SGD
    tp = [torch.nn.Parameter(torch.tensor(p)) for p in params_np]
    opt = SGD(tp, lr=cfg.lr,
              in_momentum=cfg.in_momentum_factor if cfg.in_momentum else 0,
              out_momentum=cfg.out_momentum_factor if cfg.out_momentum else 0,
              nesterov=cfg.use_nesterov,
              weight_decay=cfg.weight_decay)
    return tp, opt


@pytest.mark.parametrize("cfg", [
    OptimConfig(lr=0.1, weight_decay=0.0),
    OptimConfig(lr=0.1, weight_decay=0.01),
    OptimConfig(lr=0.05, weight_decay=0.0, in_momentum=True,
                in_momentum_factor=0.9),
    OptimConfig(lr=0.05, weight_decay=0.01, in_momentum=True,
                in_momentum_factor=0.9, use_nesterov=True),
])
def test_local_step_matches_reference(cfg):
    import torch
    rng = np.random.RandomState(0)
    params_np = [rng.randn(4, 3).astype(np.float32),
                 rng.randn(3).astype(np.float32)]
    grads_np = [[rng.randn(*p.shape).astype(np.float32) for p in params_np]
                for _ in range(4)]

    tp, topt = _torch_sgd(params_np, cfg)
    jparams = [jnp.asarray(p) for p in params_np]
    jstate = fopt.init_sgd(jparams)

    for g in grads_np:
        for p, gi in zip(tp, g):
            p.grad = torch.tensor(gi)
        topt.step(apply_lr=True, apply_in_momentum=cfg.in_momentum)
        jgrads = [jnp.asarray(gi) for gi in g]
        jparams, jstate = fopt.sgd_local_step(jparams, jgrads, jstate,
                                              cfg.lr, cfg)
        for p_t, p_j in zip(tp, jparams):
            np.testing.assert_allclose(p_t.detach().numpy(), np.asarray(p_j),
                                       atol=1e-6, rtol=1e-5)


@pytest.mark.parametrize("cfg,scale", [
    (OptimConfig(lr=0.1, weight_decay=0.01), 1.0),
    (OptimConfig(lr=0.1, weight_decay=0.01), 0.5),
    (OptimConfig(lr=0.1, weight_decay=0.0, out_momentum=True,
                 out_momentum_factor=0.9), 1.0),
])
def test_server_step_matches_reference(cfg, scale):
    """Server step must NOT apply weight decay or lr (sgd.py:99-100,125-128)."""
    import torch
    rng = np.random.RandomState(1)
    params_np = [rng.randn(5).astype(np.float32)]
    deltas = [[rng.randn(5).astype(np.float32) for _ in params_np]
              for _ in range(3)]

    tp, topt = _torch_sgd(params_np, cfg)
    jparams = [jnp.asarray(p) for p in params_np]
    jstate = fopt.init_sgd(jparams)

    for d in deltas:
        for p, di in zip(tp, d):
            p.grad = torch.tensor(di)
        topt.step(apply_lr=False, scale=scale, apply_in_momentum=False,
                  apply_out_momentum=cfg.out_momentum)
        jd = [jnp.asarray(di) for di in d]
        jparams, jstate = fopt.sgd_server_step(jparams, jd, jstate, scale, cfg)
        for p_t, p_j in zip(tp, jparams):
            np.testing.assert_allclose(p_t.detach().numpy(), np.asarray(p_j),
                                       atol=1e-6, rtol=1e-5)


def test_adam_decreases_quadratic():
    cfg = OptimConfig(optimizer="adam", lr=0.1, weight_decay=0.0)
    params = {"w": jnp.asarray([5.0, -3.0])}
    state = fopt.init_adam(params)
    loss = lambda p: jnp.sum(p["w"] ** 2)
    for _ in range(50):
        grads = jax.grad(loss)(params)
        params, state = fopt.adam_local_step(params, grads, state, 0.1, cfg)
    assert float(loss(params)) < 1.0


def test_adamw_correct_wd_differs():
    cfg_l2 = OptimConfig(optimizer="adam", lr=0.1, weight_decay=0.1,
                         correct_wd=False)
    cfg_dec = OptimConfig(optimizer="adam", lr=0.1, weight_decay=0.1,
                          correct_wd=True)
    params = {"w": jnp.asarray([5.0])}
    grads = {"w": jnp.asarray([1.0])}
    p1, _ = fopt.adam_local_step(params, grads, fopt.init_adam(params), 0.1,
                                 cfg_l2)
    p2, _ = fopt.adam_local_step(params, grads, fopt.init_adam(params), 0.1,
                                 cfg_dec)
    assert not np.allclose(np.asarray(p1["w"]), np.asarray(p2["w"]))


def test_vmap_batch_of_optimizers():
    """Per-client optimizers = one vmapped functional step (the design that
    replaces the reference's per-process optimizer objects)."""
    cfg = OptimConfig(lr=0.1, weight_decay=0.0, in_momentum=True,
                      in_momentum_factor=0.9)
    C = 4
    params = {"w": jnp.arange(C * 3, dtype=jnp.float32).reshape(C, 3)}
    grads = {"w": jnp.ones((C, 3))}
    state = fopt.init_sgd(params)

    step = jax.vmap(lambda p, g, s: fopt.sgd_local_step(p, g, s, 0.1, cfg))
    new_params, new_state = step(params, grads, state)
    np.testing.assert_allclose(np.asarray(new_params["w"]),
                               np.asarray(params["w"]) - 0.1, atol=1e-6)


class TestWeightDecayExclusion:
    """``wd_skip_norm_bias`` (ISSUE 3 satellite). Default OFF = the
    reference's uniform decay over every parameter (sgd.py:96-101
    decays the whole param group, BN scale/shift included) — parity
    runs must keep that bias-but-faithful behavior. The opt-in applies
    the standard exclusion: leaves named 'scale'/'bias' (the zoo's
    norm affine pairs and layer biases) decay with coefficient 0."""

    def params(self):
        return {
            "Conv_0": {"kernel": jnp.ones((2, 2)),
                       "bias": jnp.ones((2,))},
            "BatchStatsNorm_0": {"scale": jnp.ones((3,)),
                                 "bias": jnp.ones((3,))},
        }

    def test_default_decays_uniformly(self):
        cfg = OptimConfig(lr=1.0, weight_decay=0.1)
        p = self.params()
        grads = jax.tree.map(jnp.zeros_like, p)
        new_p, _ = fopt.sgd_local_step(p, grads, fopt.init_sgd(p), 1.0,
                                       cfg)
        for leaf in jax.tree.leaves(new_p):
            np.testing.assert_allclose(np.asarray(leaf), 0.9)

    def test_opt_in_skips_norm_and_bias(self):
        cfg = OptimConfig(lr=1.0, weight_decay=0.1,
                          wd_skip_norm_bias=True)
        p = self.params()
        grads = jax.tree.map(jnp.zeros_like, p)
        new_p, _ = fopt.sgd_local_step(p, grads, fopt.init_sgd(p), 1.0,
                                       cfg)
        np.testing.assert_allclose(np.asarray(new_p["Conv_0"]["kernel"]),
                                   0.9)  # decayed
        for leaf in (new_p["Conv_0"]["bias"],
                     new_p["BatchStatsNorm_0"]["scale"],
                     new_p["BatchStatsNorm_0"]["bias"]):
            np.testing.assert_allclose(np.asarray(leaf), 1.0)  # skipped

    @pytest.mark.parametrize("correct_wd", [False, True])
    def test_adam_both_decay_forms_respect_exclusion(self, correct_wd):
        cfg = OptimConfig(optimizer="adam", lr=0.1, weight_decay=0.1,
                          correct_wd=correct_wd,
                          wd_skip_norm_bias=True)
        cfg0 = OptimConfig(optimizer="adam", lr=0.1, weight_decay=0.0,
                           correct_wd=correct_wd)
        p = self.params()
        grads = jax.tree.map(jnp.zeros_like, p)
        new_p, _ = fopt.adam_local_step(p, grads, fopt.init_adam(p),
                                        0.1, cfg)
        ref_p, _ = fopt.adam_local_step(p, grads, fopt.init_adam(p),
                                        0.1, cfg0)
        # skipped leaves behave exactly as with wd=0...
        np.testing.assert_allclose(
            np.asarray(new_p["BatchStatsNorm_0"]["scale"]),
            np.asarray(ref_p["BatchStatsNorm_0"]["scale"]))
        # ...while the kernel is decayed
        assert not np.allclose(np.asarray(new_p["Conv_0"]["kernel"]),
                               np.asarray(ref_p["Conv_0"]["kernel"]))

    def test_exclusion_works_under_vmap_and_jit(self):
        """The engine applies the optimizer inside jit (and under vmap
        on the fused path); the path-based rule is static so it must
        trace cleanly."""
        cfg = OptimConfig(lr=0.5, weight_decay=0.2,
                          wd_skip_norm_bias=True)
        C = 3
        p = {"Dense_0": {"kernel": jnp.ones((C, 2)),
                         "bias": jnp.ones((C,))}}
        grads = jax.tree.map(jnp.zeros_like, p)
        state = fopt.init_sgd(p)
        step = jax.jit(jax.vmap(
            lambda pp, gg, ss: fopt.sgd_local_step(pp, gg, ss, 0.5,
                                                   cfg)))
        new_p, _ = step(p, grads, state)
        np.testing.assert_allclose(np.asarray(new_p["Dense_0"]["kernel"]),
                                   1.0 - 0.5 * 0.2)
        np.testing.assert_allclose(np.asarray(new_p["Dense_0"]["bias"]),
                                   1.0)
