"""Dual-mode optimizer parity tests.

The reference SGD (optimizers/sgd.py:67-129) is exercised directly (torch
cpu) on the same small problems and must agree with the functional JAX
rebuild step for step — local steps (apply_lr=True) and server steps
(apply_lr=False, scale=s, out-momentum).
"""
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.config import OptimConfig
from fedtorch_tpu.core import optim as fopt

sys.path.insert(0, "/root/reference")


def _torch_sgd(params_np, cfg: OptimConfig):
    import torch
    from fedtorch.components.optimizers.sgd import SGD
    tp = [torch.nn.Parameter(torch.tensor(p)) for p in params_np]
    opt = SGD(tp, lr=cfg.lr,
              in_momentum=cfg.in_momentum_factor if cfg.in_momentum else 0,
              out_momentum=cfg.out_momentum_factor if cfg.out_momentum else 0,
              nesterov=cfg.use_nesterov,
              weight_decay=cfg.weight_decay)
    return tp, opt


@pytest.mark.parametrize("cfg", [
    OptimConfig(lr=0.1, weight_decay=0.0),
    OptimConfig(lr=0.1, weight_decay=0.01),
    OptimConfig(lr=0.05, weight_decay=0.0, in_momentum=True,
                in_momentum_factor=0.9),
    OptimConfig(lr=0.05, weight_decay=0.01, in_momentum=True,
                in_momentum_factor=0.9, use_nesterov=True),
])
def test_local_step_matches_reference(cfg):
    import torch
    rng = np.random.RandomState(0)
    params_np = [rng.randn(4, 3).astype(np.float32),
                 rng.randn(3).astype(np.float32)]
    grads_np = [[rng.randn(*p.shape).astype(np.float32) for p in params_np]
                for _ in range(4)]

    tp, topt = _torch_sgd(params_np, cfg)
    jparams = [jnp.asarray(p) for p in params_np]
    jstate = fopt.init_sgd(jparams)

    for g in grads_np:
        for p, gi in zip(tp, g):
            p.grad = torch.tensor(gi)
        topt.step(apply_lr=True, apply_in_momentum=cfg.in_momentum)
        jgrads = [jnp.asarray(gi) for gi in g]
        jparams, jstate = fopt.sgd_local_step(jparams, jgrads, jstate,
                                              cfg.lr, cfg)
        for p_t, p_j in zip(tp, jparams):
            np.testing.assert_allclose(p_t.detach().numpy(), np.asarray(p_j),
                                       atol=1e-6, rtol=1e-5)


@pytest.mark.parametrize("cfg,scale", [
    (OptimConfig(lr=0.1, weight_decay=0.01), 1.0),
    (OptimConfig(lr=0.1, weight_decay=0.01), 0.5),
    (OptimConfig(lr=0.1, weight_decay=0.0, out_momentum=True,
                 out_momentum_factor=0.9), 1.0),
])
def test_server_step_matches_reference(cfg, scale):
    """Server step must NOT apply weight decay or lr (sgd.py:99-100,125-128)."""
    import torch
    rng = np.random.RandomState(1)
    params_np = [rng.randn(5).astype(np.float32)]
    deltas = [[rng.randn(5).astype(np.float32) for _ in params_np]
              for _ in range(3)]

    tp, topt = _torch_sgd(params_np, cfg)
    jparams = [jnp.asarray(p) for p in params_np]
    jstate = fopt.init_sgd(jparams)

    for d in deltas:
        for p, di in zip(tp, d):
            p.grad = torch.tensor(di)
        topt.step(apply_lr=False, scale=scale, apply_in_momentum=False,
                  apply_out_momentum=cfg.out_momentum)
        jd = [jnp.asarray(di) for di in d]
        jparams, jstate = fopt.sgd_server_step(jparams, jd, jstate, scale, cfg)
        for p_t, p_j in zip(tp, jparams):
            np.testing.assert_allclose(p_t.detach().numpy(), np.asarray(p_j),
                                       atol=1e-6, rtol=1e-5)


def test_adam_decreases_quadratic():
    cfg = OptimConfig(optimizer="adam", lr=0.1, weight_decay=0.0)
    params = {"w": jnp.asarray([5.0, -3.0])}
    state = fopt.init_adam(params)
    loss = lambda p: jnp.sum(p["w"] ** 2)
    for _ in range(50):
        grads = jax.grad(loss)(params)
        params, state = fopt.adam_local_step(params, grads, state, 0.1, cfg)
    assert float(loss(params)) < 1.0


def test_adamw_correct_wd_differs():
    cfg_l2 = OptimConfig(optimizer="adam", lr=0.1, weight_decay=0.1,
                         correct_wd=False)
    cfg_dec = OptimConfig(optimizer="adam", lr=0.1, weight_decay=0.1,
                          correct_wd=True)
    params = {"w": jnp.asarray([5.0])}
    grads = {"w": jnp.asarray([1.0])}
    p1, _ = fopt.adam_local_step(params, grads, fopt.init_adam(params), 0.1,
                                 cfg_l2)
    p2, _ = fopt.adam_local_step(params, grads, fopt.init_adam(params), 0.1,
                                 cfg_dec)
    assert not np.allclose(np.asarray(p1["w"]), np.asarray(p2["w"]))


def test_vmap_batch_of_optimizers():
    """Per-client optimizers = one vmapped functional step (the design that
    replaces the reference's per-process optimizer objects)."""
    cfg = OptimConfig(lr=0.1, weight_decay=0.0, in_momentum=True,
                      in_momentum_factor=0.9)
    C = 4
    params = {"w": jnp.arange(C * 3, dtype=jnp.float32).reshape(C, 3)}
    grads = {"w": jnp.ones((C, 3))}
    state = fopt.init_sgd(params)

    step = jax.vmap(lambda p, g, s: fopt.sgd_local_step(p, g, s, 0.1, cfg))
    new_params, new_state = step(params, grads, state)
    np.testing.assert_allclose(np.asarray(new_params["w"]),
                               np.asarray(params["w"]) - 0.1, atol=1e-6)
