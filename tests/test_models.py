"""Model zoo tests: shapes, param counts vs the torch reference, and
jit/vmap usability of every architecture."""
import sys
import types

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.config import DataConfig, ExperimentConfig, ModelConfig
from fedtorch_tpu.models import define_model

sys.path.insert(0, "/root/reference")


def _cfg(arch, dataset, **model_kw):
    return ExperimentConfig(data=DataConfig(dataset=dataset),
                            model=ModelConfig(arch=arch, **model_kw))


def _param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def _torch_param_count(model):
    return sum(p.numel() for p in model.parameters())


def _ref_args(arch, dataset, **kw):
    ns = types.SimpleNamespace(
        arch=arch, data=dataset, mlp_num_layers=2, mlp_hidden_size=500,
        drop_rate=0.0, vocab_size=86, rnn_hidden_size=50, rnn_seq_len=50,
        batch_size=4, federated_type="fedavg", wideresnet_widen_factor=4,
        densenet_growth_rate=12, densenet_bc_mode=False,
        densenet_compression=0.5)
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


@pytest.mark.parametrize("arch,dataset,shape", [
    ("logistic_regression", "mnist", (4, 784)),
    ("robust_logistic_regression", "mnist", (4, 784)),
    ("least_square", "MSD", (4, 90)),
    ("robust_least_square", "MSD", (4, 90)),
    ("mlp", "mnist", (4, 784)),
    ("robust_mlp", "cifar10", (4, 3072)),
    ("cnn", "mnist", (4, 28, 28, 1)),
    ("cnn", "cifar10", (4, 32, 32, 3)),
    ("resnet20", "cifar10", (4, 32, 32, 3)),
    ("resnet50", "cifar10", (4, 32, 32, 3)),
    ("wideresnet28", "cifar10", (4, 32, 32, 3)),
    ("densenet40", "cifar10", (4, 32, 32, 3)),
])
def test_forward_shapes(arch, dataset, shape):
    model = define_model(_cfg(arch, dataset))
    params = model.init(jax.random.key(0))
    x = jnp.zeros(shape)
    out = model.apply(params, x)
    expected_classes = {"mnist": 10, "cifar10": 10, "MSD": 1}[dataset]
    assert out.shape == (4, expected_classes)


@pytest.mark.parametrize("arch,dataset,ref_builder", [
    ("logistic_regression", "mnist", "logistic_regression"),
    ("mlp", "mnist", "mlp"),
    ("cnn", "mnist", "cnn"),
    ("cnn", "cifar10", "cnn"),
    ("resnet20", "cifar10", "resnet"),
    ("resnet56", "cifar10", "resnet"),
    ("wideresnet28", "cifar10", "wideresnet"),
])
def test_param_count_matches_reference(arch, dataset, ref_builder):
    """Same trainable parameter count as the torch model => same capacity.

    BN differences: torch BatchNorm holds 2 learnable params per channel,
    as does our batch-stats norm — so counts line up exactly."""
    pytest.importorskip(
        "fedtorch",
        reason="reference checkout not mounted at /root/reference")
    import fedtorch.components.models as ref_models
    ref = ref_models.__dict__[ref_builder](_ref_args(arch, dataset))
    model = define_model(_cfg(arch, dataset))
    params = model.init(jax.random.key(0))
    assert _param_count(params) == _torch_param_count(ref)


def test_logistic_regression_zero_init():
    model = define_model(_cfg("logistic_regression", "mnist"))
    params = model.init(jax.random.key(0))
    for leaf in jax.tree.leaves(params):
        assert float(jnp.abs(leaf).max()) == 0.0


def test_robust_model_has_noise_param():
    model = define_model(_cfg("robust_logistic_regression", "mnist"))
    assert model.has_noise_param
    params = model.init(jax.random.key(0))
    assert "noise" in params
    # N(0, 0.001) init
    assert float(jnp.abs(params["noise"]).max()) < 0.01
    assert float(jnp.abs(params["noise"]).max()) > 0.0


def test_rnn_carry_threading():
    model = define_model(_cfg("rnn", "shakespeare"))
    params = model.init(jax.random.key(0))
    tokens = jnp.ones((4, 50), jnp.int32)
    carry = model.init_carry(4)
    logits, carry2 = model.apply(params, tokens, carry=carry)
    assert logits.shape == (4, 50, 86)
    assert carry2.shape == carry.shape
    # hidden state actually progresses
    assert float(jnp.max(jnp.abs(carry2))) > 0.0
    # param count parity with reference GRU: torch's cuDNN-style GRU keeps
    # redundant additive double biases (b_ih + b_hh) on the r and z gates;
    # flax's GRUCell folds them. Identical function class, 2*hidden fewer
    # raw parameters.
    pytest.importorskip(
        "fedtorch",
        reason="reference checkout not mounted at /root/reference")
    import fedtorch.components.models as ref_models
    ref = ref_models.rnn(_ref_args("rnn", "shakespeare"))
    assert _param_count(params) == _torch_param_count(ref) - 2 * 50


def test_vmap_per_client_params():
    """A batch of per-client models — the core federated layout."""
    model = define_model(_cfg("mlp", "mnist"))
    keys = jax.random.split(jax.random.key(0), 3)
    params = jax.vmap(model.init)(keys)
    x = jnp.ones((3, 5, 784))
    out = jax.vmap(lambda p, xi: model.apply(p, xi))(params, x)
    assert out.shape == (3, 5, 10)


def test_jit_forward():
    model = define_model(_cfg("resnet20", "cifar10"))
    params = model.init(jax.random.key(0))
    f = jax.jit(lambda p, x: model.apply(p, x))
    out = f(params, jnp.ones((2, 32, 32, 3)))
    assert out.shape == (2, 10)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_groupnorm_variant():
    model = define_model(_cfg("resnet20", "cifar10", norm="gn"))
    params = model.init(jax.random.key(0))
    out = model.apply(params, jnp.ones((2, 32, 32, 3)))
    assert out.shape == (2, 10)


def test_dropout_needs_rng_and_is_stochastic():
    model = define_model(_cfg("mlp", "mnist", drop_rate=0.5))
    params = model.init(jax.random.key(0))
    # distinct rows: identical rows would be collapsed to zero by the
    # batch-stats norm regardless of dropout
    x = jax.random.normal(jax.random.key(0), (4, 784))
    o1 = model.apply(params, x, train=True, rng=jax.random.key(1))
    o2 = model.apply(params, x, train=True, rng=jax.random.key(2))
    assert not np.allclose(np.asarray(o1), np.asarray(o2))
    # eval is deterministic
    e1 = model.apply(params, x)
    e2 = model.apply(params, x)
    np.testing.assert_allclose(np.asarray(e1), np.asarray(e2))


def _cfg_dtype(arch, dataset, dtype, **model_kw):
    from fedtorch_tpu.config import MeshConfig
    return ExperimentConfig(data=DataConfig(dataset=dataset),
                            model=ModelConfig(arch=arch, **model_kw),
                            mesh=MeshConfig(compute_dtype=dtype))


@pytest.mark.parametrize("arch,dataset", [
    ("rnn", "shakespeare"),
    ("logistic_regression", "mnist"),
    ("robust_logistic_regression", "mnist"),
    ("least_square", "MSD"),
    ("transformer", "shakespeare"),
])
def test_bf16_compute_dtype_wired(arch, dataset):
    """compute_dtype=bfloat16 must reach every model family: params stay
    f32 (mixed precision keeps master weights), the forward runs finite,
    and training (grad step) stays finite. Closes the
    models/__init__ warning path for the rnn/linear tail."""
    model = define_model(_cfg_dtype(arch, dataset, "bfloat16"))
    params = model.init(jax.random.key(0))
    # master params stay f32
    for leaf in jax.tree.leaves(params):
        assert leaf.dtype == jnp.float32, leaf.dtype
    if arch == "rnn":
        x = jnp.ones((4, 50), jnp.int32)
        carry = model.init_carry(4)
        assert carry.dtype == jnp.bfloat16
        logits, carry2 = model.apply(params, x, carry=carry)
        assert carry2.dtype == jnp.bfloat16
    elif arch == "transformer":
        x = jnp.ones((4, 50), jnp.int32)
        logits = model.apply(params, x)
    else:
        x = jnp.ones_like(model.sample_input)
        logits = model.apply(params, x)
    assert bool(jnp.all(jnp.isfinite(logits.astype(jnp.float32))))


def test_bf16_gru_training_step_finite_and_f32_invariant():
    """One SGD step on the bf16 GRU: loss finite, updated params remain
    f32 (VERDICT r1 item 7 done-criteria)."""
    from fedtorch_tpu.core.losses import make_criterion

    model = define_model(_cfg_dtype("rnn", "shakespeare", "bfloat16"))
    params = model.init(jax.random.key(0))
    criterion = make_criterion(False)
    tokens = jax.random.randint(jax.random.key(1), (4, 50), 0, 86)
    targets = jax.random.randint(jax.random.key(2), (4, 50), 0, 86)

    def loss_fn(p):
        logits, _ = model.apply(p, tokens, carry=model.init_carry(4))
        return criterion(logits, targets)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    new_params = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
    for leaf in jax.tree.leaves(new_params):
        assert leaf.dtype == jnp.float32
    assert np.isfinite(float(loss_fn(new_params)))


def test_unknown_arch_raises():
    with pytest.raises(ValueError):
        define_model(_cfg("transformerXL", "mnist"))
