"""Tier-1 wrapper for the lint gate (scripts/lint_suite.py).

Runs the full suite in-process — the custom analyzer is stdlib-only
AST walking, so this stays in the fast lane — and pins down the gate
semantics: clean tree passes, a NEW hazard fails, a baselined or
suppressed one does not.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import lint_suite  # noqa: E402


def test_gate_is_clean():
    """The checked-in tree must pass its own gate: no tracing-hazard
    regressions vs the baseline (ruff half auto-skips when absent)."""
    assert lint_suite.main([]) == 0


def test_gate_fails_on_new_finding(tmp_path):
    """A module with a fresh hazard (host sync on a jnp expression)
    must fail the gate — the baseline only covers accepted history."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    return float(jnp.sum(x))\n")
    rc = lint_suite.run_tracing_lint([str(bad), "--root", str(tmp_path)])
    assert rc == 1


def test_gate_respects_baseline(tmp_path):
    """The same findings accepted into a baseline pass the gate."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    return float(jnp.sum(x))\n")
    base = tmp_path / "base.json"
    args = [str(bad), "--root", str(tmp_path), "--baseline", str(base)]
    assert lint_suite.run_tracing_lint(
        args + ["--write-baseline"]) == 0
    assert lint_suite.run_tracing_lint(args) == 0
    # a SECOND identical hazard exceeds the baselined multiset
    bad.write_text(
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    return float(jnp.sum(x))\n"
        "def g(x):\n"
        "    return float(jnp.max(x))\n")
    assert lint_suite.run_tracing_lint(args) == 1


def test_cli_subcommand_entry():
    """`python -m fedtorch_tpu.cli lint` routes to the analyzer
    without initializing jax (it must stay importable/cheap)."""
    proc = subprocess.run(
        [sys.executable, "-m", "fedtorch_tpu.cli", "lint", "--explain"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    assert "FTL001" in proc.stdout and "FTL005" in proc.stdout


@pytest.mark.parametrize("rule", ["FTL001", "FTL002", "FTL003",
                                  "FTL004", "FTL005"])
def test_baseline_or_clean_per_rule(rule):
    """Every rule class is live: the analyzer knows it and --explain
    documents it (regression guard for the registry)."""
    from fedtorch_tpu.lint.rules import RULES
    assert rule in RULES
    assert RULES[rule].hint
