"""Per-block rematerialization knob (MeshConfig.remat / --remat):
same params, same outputs, same gradients — only the backward's
activation-memory/FLOPs trade changes."""
import jax
import jax.numpy as jnp
import numpy as np

from fedtorch_tpu.models.resnet import ResNetCifar
from fedtorch_tpu.models.transformer import TransformerLM


def _tree_max_err(a, b):
    return max(float(jnp.max(jnp.abs(x - y))) for x, y in zip(
        jax.tree.leaves(a), jax.tree.leaves(b)))


class TestResNetRemat:
    def test_same_params_outputs_grads(self):
        x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3))
        y = jax.random.randint(jax.random.key(2), (4,), 0, 10)
        plain = ResNetCifar(dataset="cifar10", size=8, norm="gn")
        remat = ResNetCifar(dataset="cifar10", size=8, norm="gn",
                            remat=True)
        params = plain.init(jax.random.key(0), x)["params"]
        # the lifted remat must not change the param tree
        p2 = remat.init(jax.random.key(0), x)["params"]
        assert jax.tree.structure(params) == jax.tree.structure(p2)

        out_a = plain.apply({"params": params}, x, train=True)
        out_b = remat.apply({"params": params}, x, train=True)
        np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                                   atol=1e-6)

        def loss(m):
            def f(p):
                logits = m.apply({"params": p}, x, train=True)
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(jnp.take_along_axis(
                    logp, y[:, None], axis=-1))
            return f

        ga = jax.grad(loss(plain))(params)
        gb = jax.grad(loss(remat))(params)
        assert _tree_max_err(ga, gb) < 1e-6


class TestWideDenseRemat:
    def test_wideresnet_parity(self):
        from fedtorch_tpu.models.wideresnet import WideResNet
        x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
        plain = WideResNet(dataset="cifar10", depth=10, widen_factor=1,
                           norm="gn")
        remat = WideResNet(dataset="cifar10", depth=10, widen_factor=1,
                           norm="gn", remat=True)
        params = plain.init(jax.random.key(0), x)["params"]
        assert jax.tree.structure(params) == jax.tree.structure(
            remat.init(jax.random.key(0), x)["params"])
        np.testing.assert_allclose(
            np.asarray(plain.apply({"params": params}, x)),
            np.asarray(remat.apply({"params": params}, x)), atol=1e-6)
        ga = jax.grad(lambda p: jnp.sum(
            plain.apply({"params": p}, x) ** 2))(params)
        gb = jax.grad(lambda p: jnp.sum(
            remat.apply({"params": p}, x) ** 2))(params)
        assert _tree_max_err(ga, gb) < 1e-5

    def test_densenet_parity(self):
        from fedtorch_tpu.models.densenet import DenseNet
        x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
        plain = DenseNet(dataset="cifar10", depth=13, growth_rate=4,
                         norm="gn")
        remat = DenseNet(dataset="cifar10", depth=13, growth_rate=4,
                         norm="gn", remat=True)
        params = plain.init(jax.random.key(0), x)["params"]
        assert jax.tree.structure(params) == jax.tree.structure(
            remat.init(jax.random.key(0), x)["params"])
        np.testing.assert_allclose(
            np.asarray(plain.apply({"params": params}, x)),
            np.asarray(remat.apply({"params": params}, x)), atol=1e-6)

    def test_unsupported_arch_warns(self):
        import warnings
        from fedtorch_tpu.config import (ExperimentConfig, MeshConfig,
                                         ModelConfig)
        from fedtorch_tpu.models import define_model
        cfg = ExperimentConfig(
            model=ModelConfig(arch="mlp"),
            mesh=MeshConfig(remat=True)).finalize()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            define_model(cfg, batch_size=2)
        assert any("remat has no effect" in str(x.message) for x in w)


class TestTransformerRemat:
    def test_same_outputs_grads_with_flash_and_moe(self):
        """remat composes with the flash attention backend and MoE
        blocks (the memory-hungry configs it exists for)."""
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 32)
        kw = dict(vocab_size=32, d_model=16, num_heads=2, num_layers=2,
                  max_len=32, num_experts=2, capacity_factor=1.5,
                  attention="flash")
        plain = TransformerLM(**kw)
        remat = TransformerLM(**kw, remat=True)
        params = plain.init(jax.random.key(0), toks)["params"]
        out_a = plain.apply({"params": params}, toks)
        out_b = remat.apply({"params": params}, toks)
        np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                                   atol=1e-6)
        ga = jax.grad(lambda p: jnp.sum(
            plain.apply({"params": p}, toks) ** 2))(params)
        gb = jax.grad(lambda p: jnp.sum(
            remat.apply({"params": p}, toks) ** 2))(params)
        assert _tree_max_err(ga, gb) < 1e-5

    def test_pipeline_params_compatible(self):
        """A remat'd model's params still stack/pipeline, and the
        pipeline honors remat: the stage body wraps _Block in nn.remat
        exactly as TransformerLM.setup does (ADVICE r3), so activation
        memory under PP matches the flag's promise and outputs are
        unchanged."""
        import numpy as np
        from jax.sharding import Mesh
        from fedtorch_tpu.parallel.pipeline import pipeline_apply

        model = TransformerLM(vocab_size=32, d_model=16, num_heads=2,
                              num_layers=4, max_len=16, remat=True)
        toks = jax.random.randint(jax.random.key(1), (4, 16), 0, 32)
        params = model.init(jax.random.key(0), toks)["params"]
        ref = model.apply({"params": params}, toks)
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("pp",))
        out = pipeline_apply(model, params, toks, mesh,
                             num_microbatches=2)
        err = float(jnp.max(jnp.abs(out - ref)))
        assert err < 1e-3


def test_config_surface_round():
    """--remat threads MeshConfig -> define_model -> a federated round."""
    import numpy as np
    from fedtorch_tpu.algorithms import make_algorithm
    from fedtorch_tpu.config import (
        DataConfig, ExperimentConfig, FederatedConfig, MeshConfig,
        ModelConfig, OptimConfig, TrainConfig,
    )
    from fedtorch_tpu.data.batching import stack_partitions
    from fedtorch_tpu.models import define_model
    from fedtorch_tpu.parallel import FederatedTrainer

    cfg = ExperimentConfig(
        data=DataConfig(dataset="cifar10", batch_size=4),
        federated=FederatedConfig(federated=True, num_clients=4,
                                  online_client_rate=0.5,
                                  algorithm="fedavg",
                                  sync_type="local_step"),
        model=ModelConfig(arch="resnet8", norm="gn"),
        optim=OptimConfig(lr=0.05),
        train=TrainConfig(local_step=2),
        mesh=MeshConfig(num_devices=1, remat=True),
    ).finalize()
    model = define_model(cfg, batch_size=4)
    assert model.module.remat
    rng = np.random.RandomState(0)
    feats = rng.randn(32, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, 32)
    parts = [np.arange(i * 8, (i + 1) * 8) for i in range(4)]
    data = stack_partitions(feats, labels, parts)
    trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data)
    server, clients = trainer.init_state(jax.random.key(0))
    _, _, m = trainer.run_round(server, clients)
    loss = float(m.train_loss.sum() / m.online_mask.sum())
    assert np.isfinite(loss)
