"""Format-faithful fixture generators for the external dataset formats
(VERDICT r3 #7 — zero-egress fallback).

The real files cannot be downloaded in this container, so these
generators reproduce the PUBLIC specs of each format byte-faithfully —
not just "something the reader happens to parse". Faithfulness notes
cite the public format documentation / the reference implementation
that consumed the real files.

TFF federated HDF5 (fed_emnist*, shakespeare — the layout
`HDF5ClientData` reads, ref loader/utils.py:57-86):
  - one root group ``examples``; one subgroup per client id
  - EMNIST client ids are writer ids ``f####_##`` (e.g. ``f0000_14``);
    Shakespeare client ids are ``<PLAY>_<CHARACTER>`` upper-snake
  - EMNIST features: ``pixels`` float32 [N, 28, 28] in [0, 1] with
    INVERTED background (1.0 = white paper, digits dark — the TFF
    convention, opposite of torchvision MNIST), ``label`` int32 [N]
  - Shakespeare features: ``snippets`` — a variable-length byte-string
    dataset, MULTIPLE snippets per client, raw play text that includes
    characters outside the 86-char vocabulary (the reader must map
    those to index 0, not crash)

svmlight/libsvm text format (epsilon/rcv1/higgs/MSD,
ref loader/libsvm_datasets.py:26-146):
  - ``<label> <index>:<value> ...`` rows; indices 1-BASED, strictly
    ascending, and SPARSE — absent indices are implicit zeros, so rows
    have gaps and different lengths
  - ``#`` starts a comment (to end of line)
  - classification labels are {-1, +1} (rcv1, epsilon) or {0, 1}
    (higgs); MSD is REGRESSION with year labels (1922-2011)
  - distribution files are bz2-compressed (`.bz2`)
"""
from __future__ import annotations

import bz2
import os

import numpy as np


# -- TFF HDF5 ---------------------------------------------------------------

def emnist_writer_id(i: int) -> str:
    """Real fed_emnist client ids are NIST writer ids f####_##."""
    return f"f{i:04d}_{(i * 7) % 100:02d}"


def write_tff_emnist(path, clients, seed=0, label_dtype=np.int32):
    """Write a fed_emnist*-layout HDF5 file.

    ``clients``: {client_id: num_examples} (use :func:`emnist_writer_id`
    for faithful ids). Pixels are float32 in [0,1], background 1.0
    (inverted, per the TFF convention); labels ``label_dtype``.
    """
    import h5py
    rng = np.random.RandomState(seed)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with h5py.File(path, "w") as f:
        ex = f.create_group("examples")
        for cid, n in clients.items():
            g = ex.create_group(cid)
            # white background with a dark digit-ish blob
            px = np.ones((n, 28, 28), np.float32)
            for j in range(n):
                r0, c0 = rng.randint(4, 18, 2)
                px[j, r0:r0 + 8, c0:c0 + 6] = rng.rand(8, 6) * 0.3
            g.create_dataset("pixels", data=px)
            g.create_dataset(
                "label", data=rng.randint(0, 10, n).astype(label_dtype))


def write_tff_shakespeare(path, clients, seed=0):
    """Write a shakespeare-layout HDF5 file.

    ``clients``: {client_id: [snippet strings]} — pass several
    variable-length snippets per client; include out-of-vocab chars to
    exercise the reader's fallback. Ids like
    ``THE_TRAGEDY_OF_HAMLET_HAMLET`` match the real files.
    """
    import h5py
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with h5py.File(path, "w") as f:
        ex = f.create_group("examples")
        for cid, snippets in clients.items():
            g = ex.create_group(cid)
            g.create_dataset(
                "snippets",
                data=np.asarray([s.encode("utf-8") for s in snippets],
                                dtype=object),
                dtype=h5py.string_dtype())


# -- svmlight ---------------------------------------------------------------

def svmlight_rows(n_rows, n_features, *, labels, density=0.4, seed=0,
                  comments=False, precision=6):
    """Generate faithful svmlight text: sparse gapped 1-based ascending
    indices, variable row lengths, optional # comments.

    ``labels``: 'pm1' ({-1,+1}), '01' ({0,1}), or 'year' (MSD-style
    regression years).
    """
    rng = np.random.RandomState(seed)
    lines = []
    if comments:
        lines.append("# generated format-faithful fixture")
    dense = np.zeros((n_rows, n_features), np.float64)
    ys = np.zeros(n_rows, np.float64)
    for i in range(n_rows):
        if labels == "pm1":
            y = int(rng.choice([-1, 1]))
            lab = str(y)
        elif labels == "01":
            y = int(rng.choice([0, 1]))
            lab = str(y)
        elif labels == "year":
            y = int(rng.randint(1922, 2012))
            lab = str(y)
        else:
            raise ValueError(labels)
        ys[i] = y
        # sparse: each row keeps a random subset of indices (>=1 so the
        # row is never empty), strictly ascending, 1-based
        k = max(1, int(density * n_features * rng.rand() * 2))
        idx = np.sort(rng.choice(n_features, size=min(k, n_features),
                                 replace=False))
        vals = rng.randn(len(idx))
        dense[i, idx] = vals
        row = lab + " " + " ".join(
            f"{j + 1}:{v:.{precision}g}" for j, v in zip(idx, vals))
        if comments and i == 0:
            row += " # trailing comment"
        lines.append(row)
    return "\n".join(lines) + "\n", dense, ys


def write_svmlight(path, n_rows, n_features, *, labels, compress=False,
                   **kw):
    """Write svmlight text (optionally bz2, as distributed). Returns
    (dense_matrix, labels) for assertions."""
    text, dense, ys = svmlight_rows(n_rows, n_features, labels=labels,
                                    **kw)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if compress:
        with bz2.open(path, "wb") as f:
            f.write(text.encode())
    else:
        with open(path, "w") as f:
            f.write(text)
    return dense, ys
