"""Deployment-realism plane tests (docs/robustness.md "Deployment
realism"): the pluggable availability model behind both federation
planes, the sync round lifecycle (over-selection -> deadline ->
quorum), its health/supervisor escalation, and the deprecation of the
legacy straggler-knob aliasing.

The bars, per the engine-wide contracts:

* the ``default`` model reproduces the pre-availability scheduler
  draws BITWISE (recomputed here from the raw fold chain, independent
  of robustness/availability.py);
* every armed trajectory is a pure function of (seed, round/commit) —
  seeded replay is bitwise, fast-forward resume lands on the same
  event stream;
* the armed round program still traces exactly once per cell;
* sub-quorum rounds degrade (commit the renormalized partial cohort)
  instead of wedging, and 'abort' escalates into the supervisor's
  retry -> skip(cause='quorum') path.
"""
import os
import warnings

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    CheckpointConfig, DataConfig, ExperimentConfig, FaultConfig,
    FederatedConfig, ModelConfig, OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.robustness import RoundSupervisor
from fedtorch_tpu.robustness.availability import (
    LEGACY_DELAY_SALT, DefaultAvailability, TraceAvailability,
    make_availability_model, synthesize_trace,
)
from fedtorch_tpu.async_plane.scheduler import (
    AsyncSchedule, simulate_sync_round_times,
)
from fedtorch_tpu.utils.tracing import RecompilationSentinel


def make_cfg(fault, *, num_clients=8, sync_mode="sync", plane="device",
             num_comms=6, run_dir=None, rate=0.5):
    ckpt = CheckpointConfig(run_dir=run_dir, debug=False) \
        if run_dir else CheckpointConfig()
    return ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20,
                        batch_size=16, synthetic_alpha=0.5,
                        synthetic_beta=0.5, data_plane=plane),
        federated=FederatedConfig(
            federated=True, num_clients=num_clients,
            num_comms=num_comms, online_client_rate=rate,
            algorithm="fedavg", sync_type="local_step",
            sync_mode=sync_mode),
        model=ModelConfig(arch="logistic_regression"),
        optim=OptimConfig(lr=0.3, weight_decay=0.0),
        train=TrainConfig(local_step=2),
        checkpoint=ckpt,
        fault=fault,
    ).finalize()


def make_trainer(fault, **kw):
    cfg = make_cfg(fault, **kw)
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    return FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)


def fingerprint(tree):
    return [np.asarray(x).tobytes() for x in jax.tree.leaves(tree)]


def _key_state(seed):
    key = jax.random.key(seed)
    return (np.asarray(jax.random.key_data(key)),
            jax.random.key_impl(key))


def _sched(seed=0, *, num_clients=12, model=None, rate=0.4, frac=0.1,
           start_commit=0):
    kd, impl = _key_state(seed)
    return AsyncSchedule(kd, impl, num_clients=num_clients,
                         concurrency=4, buffer_size=2, ring_size=4,
                         straggler_rate=rate, straggler_step_frac=frac,
                         start_commit=start_commit, model=model)


def _commit_seq(sched, n):
    return [(cm.commit, cm.idx.tolist(), cm.version.tolist(),
             cm.dispatch.tolist(), cm.arrival_times.tolist())
            for cm in (sched.next_commit() for _ in range(n))]


# -- the default model: the legacy chain, bitwise ---------------------------
class TestDefaultModelBitwise:
    def test_first_dispatch_matches_raw_legacy_chain(self):
        """The scheduler's dispatch-0 delay equals the historical
        inline computation, recomputed here from the raw fold chain:
        u = uniform(fold(fold(key, SALT), did), (2,)), host-f64 tail
        math. A moved draw anywhere in the refactor breaks this."""
        rate, frac = 0.4, 0.1
        sched = _sched(rate=rate, frac=frac)
        d0 = next(t for t, did, *_ in sched._heap if did == 0)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            k = jax.random.fold_in(jax.random.key(0),
                                   LEGACY_DELAY_SALT)
            u = np.asarray(jax.random.uniform(
                jax.random.fold_in(k, 0), (2,)), np.float64)
        base = 1.0 + 0.25 * u[1]
        want = base * (1.0 / frac) if u[0] < rate else base
        assert d0 == want

    def test_commit_sequence_replays_and_fast_forwards(self):
        seq = _commit_seq(_sched(), 6)
        assert _commit_seq(_sched(), 6) == seq
        # a fresh instance fast-forwarded to commit 3 replays the tail
        assert _commit_seq(_sched(start_commit=3), 3) == seq[3:]

    def test_arming_dropout_leaves_legacy_columns_untouched(self):
        """avail_dropout_rate adds an INDEPENDENT third draw column:
        the delay/straggler columns (and so every arrival time) are
        bitwise those of the dropout-free model."""
        kd, impl = _key_state(0)
        key = jax.random.wrap_key_data(jnp.asarray(kd), impl=impl)
        ids = np.arange(8, dtype=np.int32)
        clients = np.zeros(8, np.int32)
        plain = DefaultAvailability(straggler_rate=0.4,
                                    straggler_step_frac=0.1)
        armed = DefaultAvailability(straggler_rate=0.4,
                                    straggler_step_frac=0.1,
                                    dropout_rate=0.5)
        u_p = np.asarray(plain.traced(key, ids, clients, ids))
        u_a = np.asarray(armed.traced(key, ids, clients, ids))
        assert u_a.shape[1] == 3
        np.testing.assert_array_equal(u_p, u_a[:, :2])

    def test_sync_round_simulation_unchanged(self):
        """simulate_sync_round_times still draws the raw legacy chain
        (it is the sync side of ASYNC_AB) — pinned against an inline
        recomputation of round 0."""
        kd, impl = _key_state(3)
        times = simulate_sync_round_times(
            kd, impl, rounds=4, k_online=5, straggler_rate=0.4,
            straggler_step_frac=0.1)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            k = jax.random.fold_in(jax.random.key(3),
                                   LEGACY_DELAY_SALT)
            u = np.asarray([jax.random.uniform(
                jax.random.fold_in(k, d), (2,)) for d in range(5)],
                np.float64)
        base = 1.0 + 0.25 * u[:, 1]
        delays = np.where(u[:, 0] < 0.4, base * 10.0, base)
        assert times[0] == delays.max()

    def test_legacy_spelling_warns_on_async(self):
        with pytest.warns(FutureWarning, match="legacy straggler-knob"):
            make_cfg(FaultConfig(straggler_rate=0.4,
                                 straggler_step_frac=0.1),
                     num_clients=12, sync_mode="async")

    def test_trace_model_spelling_does_not_warn(self):
        with warnings.catch_warnings():
            warnings.simplefilter("error", FutureWarning)
            make_cfg(FaultConfig(avail_model="trace",
                                 avail_dropout_rate=0.2),
                     num_clients=12, sync_mode="async")


# -- the trace model on the async plane -------------------------------------
class TestAsyncTraceModel:
    def _model(self):
        return TraceAvailability(dropout_rate=0.3, diurnal_period=8)

    def test_determinism_fast_forward_and_dropout_redispatch(self):
        seq = _commit_seq(_sched(model=self._model()), 6)
        s2 = _sched(model=self._model())
        assert _commit_seq(s2, 6) == seq
        assert s2.stats.dropouts > 0  # arrivals discarded+re-dispatched
        assert _commit_seq(
            _sched(model=self._model(), start_commit=3), 3) == seq[3:]

    def test_synthetic_trace_matches_model_draws(self):
        """synthesize_trace materializes the same fleet the model
        derives in-jit: class multipliers in the DEVICE_CLASSES set,
        phases in [0,1), pure function of the key."""
        kd, impl = _key_state(0)
        t1 = synthesize_trace(kd, impl, num_clients=16)
        t2 = synthesize_trace(kd, impl, num_clients=16)
        np.testing.assert_array_equal(t1["speed_multiplier"],
                                      t2["speed_multiplier"])
        assert set(np.unique(t1["speed_multiplier"])) <= {1.0, 2.0, 4.0}
        assert ((t1["diurnal_phase"] >= 0)
                & (t1["diurnal_phase"] < 1)).all()

    def test_async_trainer_end_to_end_deterministic(self):
        from fedtorch_tpu.async_plane import AsyncFederatedTrainer

        def run():
            cfg = make_cfg(FaultConfig(avail_model="trace",
                                       avail_dropout_rate=0.3,
                                       straggler_rate=0.4,
                                       straggler_step_frac=0.1),
                           num_clients=12, sync_mode="async",
                           num_comms=4)
            data = build_federated_data(cfg)
            model = define_model(cfg, batch_size=cfg.data.batch_size)
            t = AsyncFederatedTrainer(cfg, model, make_algorithm(cfg),
                                      data.train)
            server, clients = t.init_state(jax.random.key(0))
            for _ in range(4):
                server, clients, _ = t.run_round(server, clients)
            st = t.schedule_stats
            t.invalidate_stream()
            return fingerprint(server.params), st.dropouts

        fp1, drops1 = run()
        fp2, drops2 = run()
        assert fp1 == fp2
        assert drops1 == drops2 > 0


# -- the sync round lifecycle -----------------------------------------------
ARMED = dict(avail_model="trace", avail_dropout_rate=0.3,
             avail_diurnal_period=8, over_select_frac=1.5,
             avail_quorum_frac=0.5)


class TestSyncLifecycle:
    def test_counters_replay_and_trace_once(self):
        """The armed lifecycle composes with robust aggregation and
        guards: bitwise seeded replay, live counters riding the one
        batched fetch, the round program traced exactly once."""
        flt = FaultConfig(robust_agg="median", guard_updates=True,
                          **ARMED)

        def run():
            t = make_trainer(flt)
            server, clients = t.init_state(jax.random.key(0))
            totals = {"avail_dropped": 0.0, "deadline_missed": 0.0,
                      "quorum_degraded": 0.0}
            with RecompilationSentinel() as sentinel:
                for _ in range(4):
                    server, clients, m = t.run_round(server, clients)
                    for k in totals:
                        totals[k] += float(getattr(m, k))
            return (fingerprint(server.params), totals,
                    sum(sentinel.counts.values()))

        fp1, totals, traces = run()
        fp2, totals2, _ = run()
        assert fp1 == fp2 and totals == totals2
        assert traces == 1
        assert totals["avail_dropped"] + totals["deadline_missed"] > 0
        assert all(np.isfinite(np.frombuffer(b, np.float32)).all()
                   for b in fp1)

    def test_over_selection_widens_dispatch_not_acceptance(self):
        t = make_trainer(FaultConfig(**ARMED))
        assert t.k_dispatch == int(np.ceil(1.5 * t.k_online))
        server, clients = t.init_state(jax.random.key(0))
        _, _, m = t.run_round(server, clients)
        # at most k_online arrivals are accepted into aggregation
        assert float(m.online_mask.sum()) <= t.k_online

    @pytest.mark.parametrize("plane,dispatch", [
        ("device", "round"), ("stream", "round"), ("device", "scan"),
    ])
    def test_armed_cells_trace_once_and_replay(self, plane, dispatch):
        """The lifecycle is part of _round_core, so every legal sync
        builder cell carries it: per-cell trace-once + seeded
        replay."""
        flt = FaultConfig(robust_agg="trimmed_mean", **ARMED)

        def run():
            t = make_trainer(flt, plane=plane)
            server, clients = t.init_state(jax.random.key(0))
            with RecompilationSentinel() as sentinel:
                if dispatch == "scan":
                    for _ in range(2):
                        server, clients, _ = t.run_rounds(
                            server, clients, 2)
                else:
                    for _ in range(4):
                        server, clients, _ = t.run_round(
                            server, clients)
            t.invalidate_stream()
            return fingerprint(server.params), \
                sum(sentinel.counts.values())

        fp1, traces = run()
        fp2, _ = run()
        assert traces == 1
        assert fp1 == fp2

    def test_all_dropped_round_degrades_and_holds_server(self):
        """100% dropout: the accept mask is empty, renormalization
        holds the server (no NaN from a 0/0), the round still commits
        (counter advances) and is counted sub-quorum — the wedge case
        a naive deadline abort turns into a stall."""
        flt = FaultConfig(avail_dropout_rate=1.0, over_select_frac=1.5,
                          avail_quorum_frac=0.9)
        t = make_trainer(flt)
        server, clients = t.init_state(jax.random.key(0))
        p0 = fingerprint(server.params)
        server, clients, m = t.run_round(server, clients)
        assert fingerprint(server.params) == p0
        assert int(server.round) == 1
        assert float(m.quorum_degraded) == 1.0
        assert float(m.avail_dropped) == t.k_dispatch
        assert float(m.online_mask.sum()) == 0.0

    def test_disarmed_counters_stay_zero(self):
        t = make_trainer(FaultConfig())
        server, clients = t.init_state(jax.random.key(0))
        _, _, m = t.run_round(server, clients)
        assert float(m.avail_dropped) == 0.0
        assert float(m.deadline_missed) == 0.0
        assert float(m.quorum_degraded) == 0.0


# -- escalation: supervisor cause split + health intent ---------------------
class TestEscalation:
    def test_quorum_abort_skips_with_cause(self):
        causes = []
        flt = FaultConfig(supervisor=True, max_retries=1,
                          backoff_base_s=0.0,
                          avail_dropout_rate=1.0, over_select_frac=1.5,
                          avail_quorum_frac=0.9,
                          avail_quorum_action="abort")
        t = make_trainer(flt)
        sup = RoundSupervisor(t, sleep_fn=lambda s: None,
                              on_round_skipped=lambda r, c:
                              causes.append((r, c)))
        server, clients = t.init_state(jax.random.key(0))
        server, clients, _ = sup.run_round(server, clients)
        assert sup.stats.skipped_quorum == 1
        assert sup.stats.skipped_fault == 0
        assert sup.stats.retries == 1  # reseeded redraw was attempted
        assert causes == [(0, "quorum")]
        assert int(server.round) == 1  # skip advances, never wedges

    def test_fault_skip_keeps_cause_fault(self):
        causes = []
        flt = FaultConfig(nan_inject_rate=1.0, max_retries=0,
                          backoff_base_s=0.0)
        t = make_trainer(flt)
        sup = RoundSupervisor(t, sleep_fn=lambda s: None,
                              on_round_skipped=lambda r, c:
                              causes.append(c))
        server, clients = t.init_state(jax.random.key(0))
        sup.run_round(server, clients)
        assert sup.stats.skipped_fault == 1
        assert sup.stats.skipped_quorum == 0
        assert causes == ["fault"]

    def test_degrade_action_never_enters_supervisor_skip(self):
        flt = FaultConfig(supervisor=True, max_retries=1,
                          backoff_base_s=0.0,
                          avail_dropout_rate=1.0, over_select_frac=1.5,
                          avail_quorum_frac=0.9)  # action: degrade
        t = make_trainer(flt)
        sup = RoundSupervisor(t, sleep_fn=lambda s: None)
        server, clients = t.init_state(jax.random.key(0))
        for _ in range(2):
            server, clients, _ = sup.run_round(server, clients)
        assert sup.stats.skipped_rounds == 0
        assert sup.stats.healthy_rounds == 2

    def test_persistent_subquorum_writes_degraded_intent(self, tmp_path):
        from fedtorch_tpu.cli import run_experiment
        from fedtorch_tpu.telemetry import read_health
        run_dir = str(tmp_path / "avail_run")
        flt = FaultConfig(avail_dropout_rate=1.0, over_select_frac=1.5,
                          avail_quorum_frac=0.9)
        cfg = make_cfg(flt, num_comms=4, run_dir=run_dir)
        run_experiment(cfg)
        doc = read_health(run_dir)
        assert doc["intent"] == "degraded"


# -- config validation ------------------------------------------------------
class TestConfigValidation:
    def test_abort_requires_supervisor(self):
        with pytest.raises(ValueError, match="supervisor"):
            make_cfg(FaultConfig(avail_quorum_frac=0.5,
                                 avail_quorum_action="abort"))

    def test_unknown_model_rejected(self):
        with pytest.raises(ValueError, match="avail_model"):
            make_cfg(FaultConfig(avail_model="fedscale_live"))

    def test_quorum_frac_range_enforced(self):
        with pytest.raises(ValueError, match="avail_quorum_frac"):
            make_cfg(FaultConfig(avail_quorum_frac=1.5))

    def test_factory_picks_model_from_config(self):
        assert isinstance(
            make_availability_model(FaultConfig(avail_model="trace")),
            TraceAvailability)
        assert isinstance(
            make_availability_model(FaultConfig()),
            DefaultAvailability)
