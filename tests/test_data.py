"""Data layer tests: partitioners (cross-checked against the reference),
synthetic generator, and device batching."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig,
)
from fedtorch_tpu.data import (
    ClientData, build_federated_data, dirichlet_partition, epoch_permutation,
    generate_synthetic, iid_partition, label_sorted_partition, sample_batch,
    sensitive_group_partition, stack_partitions, take_batch, train_val_split,
)



class TestPartitioners:
    def test_iid_covers_all(self):
        parts = iid_partition(100, 4, seed=0)
        all_idx = np.sort(np.concatenate(parts))
        np.testing.assert_array_equal(all_idx, np.arange(100))
        assert all(len(p) == 25 for p in parts)

    def test_iid_deterministic(self):
        p1 = iid_partition(50, 5, seed=3)
        p2 = iid_partition(50, 5, seed=3)
        for a, b in zip(p1, p2):
            np.testing.assert_array_equal(a, b)

    def test_label_sorted_num_classes(self):
        labels = np.repeat(np.arange(10), 100)  # 1000 samples, 10 classes
        parts = label_sorted_partition(labels, 10, num_class_per_client=2)
        for p in parts:
            client_classes = np.unique(labels[p])
            assert len(client_classes) <= 2
            assert len(p) == 100  # 1000/(10*2) per slice, 2 slices

    def test_label_sorted_unbalanced_total(self):
        labels = np.repeat(np.arange(10), 100)
        parts = label_sorted_partition(labels, 10, num_class_per_client=2,
                                       unbalanced=True)
        sizes = np.asarray([len(p) for p in parts])
        assert sizes.sum() <= 1000
        assert sizes.std() > 0  # actually unbalanced

    def test_dirichlet_matches_reference_sizes(self):
        """Run the reference partitioner in-process and compare the exact
        per-client class allocation for the same RNG draw."""
        labels = np.repeat(np.arange(10), 50)
        n_clients = 5

        np.random.seed(7)
        probs_ref = np.random.dirichlet(10 * [0.1 / 10], n_clients)
        probs_ref[probs_ref * (500 // n_clients) < 10] = 0
        col = probs_ref.sum(0)
        col[col == 0] = 1
        expected_sizes = (probs_ref * 50 / col).astype(int)

        # our implementation uses RandomState(seed) -> same MT19937 stream
        parts = dirichlet_partition(labels, n_clients, concentration=0.1,
                                    seed=7)
        for c, p in enumerate(parts):
            counts = np.bincount(labels[p], minlength=10)
            np.testing.assert_array_equal(counts, expected_sizes[c])

    def test_dirichlet_is_skewed(self):
        labels = np.repeat(np.arange(10), 500)
        parts = dirichlet_partition(labels, 10, seed=1)
        # with concentration 0.1/K, clients concentrate on ~1 class
        for p in parts:
            if len(p) == 0:
                continue
            counts = np.bincount(labels[p], minlength=10)
            top_frac = counts.max() / max(counts.sum(), 1)
            assert top_frac > 0.5

    def test_sensitive_groups(self):
        sensitive = np.asarray([0, 0, 0, 0, 1, 1, 1, 1])
        parts = sensitive_group_partition(sensitive, 4)
        for i, p in enumerate(parts):
            group = 0 if i < 2 else 1
            assert np.all(sensitive[p] == group)
        with pytest.raises(ValueError):
            sensitive_group_partition(sensitive, 3)


class TestSynthetic:
    def test_shapes_and_heterogeneity(self):
        data = generate_synthetic(num_tasks=8, alpha=1.0, beta=1.0,
                                  num_dim=20)
        assert len(data.client_x) == 8
        for x, y in zip(data.client_x, data.client_y):
            assert x.shape[1] == 20
            assert x.shape[0] == y.shape[0]
            assert 350 <= x.shape[0] <= 800  # 0.8 * [500, 1000]
        assert data.test_x.shape[0] > 0

    def test_deterministic(self):
        d1 = generate_synthetic(4, seed=5)
        d2 = generate_synthetic(4, seed=5)
        np.testing.assert_array_equal(d1.client_x[0], d2.client_x[0])

    def test_regression_mode(self):
        data = generate_synthetic(4, regression=True, num_dim=10)
        assert data.client_y[0].dtype == np.float32


class TestBatching:
    def _make(self):
        feats = np.arange(40, dtype=np.float32).reshape(20, 2)
        labels = np.arange(20)
        parts = [np.arange(0, 8), np.arange(8, 20)]  # sizes 8, 12
        return stack_partitions(feats, labels, parts)

    def test_stack_pads_cyclically(self):
        cd = self._make()
        assert cd.x.shape == (2, 12, 2)
        assert list(cd.sizes) == [8, 12]
        # client 0 padding repeats its own samples
        np.testing.assert_array_equal(np.asarray(cd.y[0, 8:12]),
                                      np.asarray(cd.y[0, :4]))

    def test_epoch_permutation_covers_real_samples(self):
        perm = epoch_permutation(jax.random.key(0), jnp.asarray(8), 12)
        first8 = np.sort(np.asarray(perm[:8]))
        np.testing.assert_array_equal(first8, np.arange(8))

    def test_take_batch_epoch_semantics(self):
        cd = self._make()
        perm = epoch_permutation(jax.random.key(1), cd.sizes[0], cd.n_max)
        seen = []
        for step in range(2):  # 2 batches of 4 = full epoch of client 0
            bx, by = take_batch(cd.x[0], cd.y[0], perm, cd.sizes[0],
                                jnp.asarray(step), 4)
            seen.extend(np.asarray(by).tolist())
        assert sorted(seen) == list(range(8))

    def test_sample_batch_in_range(self):
        cd = self._make()
        bx, by = sample_batch(jax.random.key(2), cd.x[0], cd.y[0],
                              cd.sizes[0], 16)
        assert np.asarray(by).max() < 8  # never draws padding

    def test_train_val_split(self):
        parts = [np.arange(10), np.arange(10, 30)]
        tr, va = train_val_split(parts, 0.2, seed=0)
        for t, v, p in zip(tr, va, parts):
            assert len(t) + len(v) == len(p)
            assert len(set(t) & set(v)) == 0
        assert len(va[0]) == 2

    def test_zero_size_partition_raises(self):
        with pytest.raises(ValueError):
            stack_partitions(np.ones((4, 2)), np.ones(4),
                             [np.arange(4), np.zeros(0, int)])


def test_build_federated_data_synthetic():
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=20),
        federated=FederatedConfig(federated=True, num_clients=6),
    ).finalize()
    fed = build_federated_data(cfg)
    assert fed.train.num_clients == 6
    assert fed.train.x.shape[-1] == 20
    assert fed.test_x.shape[0] > 0
    assert fed.val is None


def test_build_federated_data_personal_split():
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=10),
        federated=FederatedConfig(federated=True, num_clients=4,
                                  algorithm="apfl"),
    ).finalize()
    fed = build_federated_data(cfg)
    assert fed.val is not None
    assert fed.val.num_clients == 4


def test_missing_dataset_clear_error(tmp_path):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="mnist", data_dir=str(tmp_path)),
        federated=FederatedConfig(federated=True, num_clients=2),
    ).finalize()
    with pytest.raises(FileNotFoundError, match="no network egress"):
        build_federated_data(cfg)


class TestEmnistMissingTestSplit:
    """The EMNIST train-as-test fallback is opt-in (ISSUE 3
    satellite): a missing test archive must raise, not silently score
    training rows as the test set."""

    def _write_train_h5(self, tmp_path, name="fed_emnist_digitsonly",
                        sub="emnist"):
        import h5py
        base = tmp_path / sub
        base.mkdir()
        rng = np.random.RandomState(0)
        with h5py.File(base / f"{name}_train.h5", "w") as f:
            ex = f.create_group("examples")
            for client in ("f0000_14", "f0001_41"):
                g = ex.create_group(client)
                g.create_dataset(
                    "pixels", data=rng.rand(5, 28, 28).astype("f4"))
                g.create_dataset("label", data=np.arange(5) % 10)

    def test_missing_test_split_raises(self, tmp_path):
        from fedtorch_tpu.data.datasets import load_emnist
        self._write_train_h5(tmp_path)
        with pytest.raises(FileNotFoundError,
                           match="allow_train_as_test"):
            load_emnist(str(tmp_path))

    def test_opt_in_slices_train_with_warning(self, tmp_path):
        from fedtorch_tpu.data.datasets import load_emnist
        self._write_train_h5(tmp_path)
        splits = load_emnist(str(tmp_path), allow_train_as_test=True)
        assert splits.test_x.shape[0] == min(256,
                                             splits.train_x.shape[0])
        np.testing.assert_array_equal(
            splits.test_x, splits.train_x[:splits.test_x.shape[0]])

    def test_config_threads_the_opt_in(self):
        cfg = ExperimentConfig(
            data=DataConfig(dataset="emnist", allow_train_as_test=True),
            federated=FederatedConfig(federated=True, num_clients=2),
        ).finalize()
        assert cfg.data.allow_train_as_test
        assert not DataConfig().allow_train_as_test  # loud by default
