"""Fixture-driven tests for the tracing-hazard analyzer.

Each rule gets positive controls (the hazard, asserted by exact rule
id AND line number) and negative controls (the legal idiom the rule
must NOT flag) — including the two the issue calls out explicitly:
numpy at setup time, and key reuse after an intervening fold_in.
"""
import textwrap

from fedtorch_tpu.lint import analyze_source
from fedtorch_tpu.lint.findings import (
    diff_against_baseline, load_baseline, save_baseline,
    suppressions_for_source,
)


def hits(src, rule=None):
    """[(rule, line)] findings for a dedented source snippet."""
    out = analyze_source(textwrap.dedent(src), "snippet.py")
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return [(f.rule, f.line) for f in out]


# -- FTL001: host syncs -----------------------------------------------------

def test_ftl001_float_on_jnp_expr():
    src = """\
    import jax.numpy as jnp

    def round_metrics(losses):
        a = float(jnp.sum(losses))
        b = int(jnp.argmax(losses))
        c = bool(jnp.all(losses > 0))
        return a, b, c
    """
    assert hits(src, "FTL001") == [("FTL001", 4), ("FTL001", 5),
                                   ("FTL001", 6)]


def test_ftl001_item_and_np_asarray():
    src = """\
    import numpy as np
    import jax.numpy as jnp

    def log_round(metrics):
        loss = jnp.mean(metrics)
        x = loss.item()
        y = np.asarray(jnp.exp(loss))
        return x, y
    """
    assert hits(src, "FTL001") == [("FTL001", 6), ("FTL001", 7)]


def test_ftl001_from_import_numpy_member():
    """`from numpy import asarray` must canonicalize like np.asarray —
    the bare-name alias is a real detection surface, not dead code."""
    src = """\
    import jax.numpy as jnp
    from numpy import asarray

    def fetch(metrics):
        return asarray(jnp.sum(metrics))
    """
    assert hits(src, "FTL001") == [("FTL001", 5)]


def test_ftl001_negative_host_values():
    """float() on plain host math and on device_get results is legal —
    device_get is the sanctioned batched-transfer idiom."""
    src = """\
    import jax
    import jax.numpy as jnp

    def fine(sizes, metrics):
        n = float(sum(sizes))
        host = jax.device_get({"m": jnp.mean(metrics)})
        return n + float(host["m"])
    """
    assert hits(src, "FTL001") == []


def test_ftl001_inside_jit_is_flagged():
    src = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        s = jnp.sum(x)
        return x / float(s)
    """
    assert hits(src, "FTL001") == [("FTL001", 7)]


# -- FTL002: numpy inside traced code ---------------------------------------

def test_ftl002_numpy_on_traced_value():
    src = """\
    import jax
    import numpy as np

    @jax.jit
    def bad(x, w):
        return np.dot(x, w)
    """
    assert hits(src, "FTL002") == [("FTL002", 6)]


def test_ftl002_negative_numpy_at_setup_time():
    """numpy on host data outside traced code is the LEGAL setup-time
    pattern (15 modules import numpy for exactly this)."""
    src = """\
    import numpy as np

    def build_batches(x, batch_size):
        n = np.ceil(len(x) / batch_size)
        perm = np.random.permutation(len(x))
        return np.split(x[perm], int(n))
    """
    assert hits(src, "FTL002") == []


def test_ftl002_negative_numpy_constant_inside_jit():
    """numpy math on static host constants inside jit traces to a
    constant on purpose (shape/eps math) — not flagged."""
    src = """\
    import jax
    import numpy as np

    @jax.jit
    def ok(x):
        eps = np.sqrt(2.0)
        return x * eps
    """
    assert hits(src, "FTL002") == []


def test_ftl002_reachable_from_jit():
    """Reachability: a helper called from a jitted function is traced
    even without its own decorator (intra-module closure)."""
    src = """\
    import jax
    import numpy as np

    def helper(x):
        return np.square(x)

    @jax.jit
    def outer(x):
        return helper(x)
    """
    assert hits(src, "FTL002") == [("FTL002", 5)]


# -- FTL003: PRNG discipline ------------------------------------------------

def test_ftl003_key_reuse():
    src = """\
    import jax

    def sample(key):
        a = jax.random.normal(key, (3,))
        b = jax.random.uniform(key, (3,))
        return a + b
    """
    assert hits(src, "FTL003") == [("FTL003", 5)]


def test_ftl003_negative_split_and_fold_in():
    """The two sanctioned refresh idioms: split into distinct keys,
    and rebinding through fold_in before the next consumption."""
    src = """\
    import jax

    def sample(key):
        k1, k2 = jax.random.split(key)
        a = jax.random.normal(k1, (3,))
        b = jax.random.uniform(k2, (3,))
        key = jax.random.fold_in(key, 7)
        c = jax.random.normal(key, (3,))
        key = jax.random.fold_in(key, 8)
        d = jax.random.normal(key, (3,))
        return a + b + c + d
    """
    assert hits(src, "FTL003") == []


def test_ftl003_loop_reuse():
    """A key bound outside a loop and consumed each iteration draws
    the SAME stream every pass — the silent determinism killer."""
    src = """\
    import jax

    def rounds(key, n):
        out = []
        for i in range(n):
            out.append(jax.random.normal(key, (2,)))
        return out
    """
    assert hits(src, "FTL003") == [("FTL003", 6)]


def test_ftl003_negative_fold_in_inside_loop():
    src = """\
    import jax

    def rounds(key, n):
        out = []
        for i in range(n):
            k = jax.random.fold_in(key, i)
            out.append(jax.random.normal(k, (2,)))
        return out
    """
    assert hits(src, "FTL003") == []


def test_ftl003_negative_exclusive_branches():
    """Mutually exclusive branches each consume the key once — only
    one ever runs, so this is NOT reuse (branch-local state copies
    must be deep: the per-key dicts are mutated in place)."""
    src = """\
    import jax

    def sample(key, gaussian):
        if gaussian:
            x = jax.random.normal(key, (3,))
        else:
            x = jax.random.uniform(key, (3,))
        return x
    """
    assert hits(src, "FTL003") == []


def test_ftl003_negative_split_iteration():
    """Iterating over split keys consumes a fresh key per pass."""
    src = """\
    import jax

    def batch(key, n):
        out = []
        for k in jax.random.split(key, n):
            out.append(jax.random.normal(k, (2,)))
        return out
    """
    assert hits(src, "FTL003") == []


# -- FTL004: missing donation ------------------------------------------------

def test_ftl004_rebuild_without_donation():
    src = """\
    import jax
    import jax.numpy as jnp

    def train_step(params, grads):
        new_params = jax.tree.map(lambda p, g: p - 0.1 * g,
                                  params, grads)
        return new_params

    step = jax.jit(train_step)
    """
    assert hits(src, "FTL004") == [("FTL004", 9)]


def test_ftl004_negative_with_donation():
    src = """\
    import jax
    import jax.numpy as jnp

    def train_step(params, grads):
        return jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)

    step = jax.jit(train_step, donate_argnums=(0,))
    """
    assert hits(src, "FTL004") == []


def test_ftl004_negative_scalar_output():
    """Functions returning fresh reductions (not rebuilt inputs) are
    not donation candidates."""
    src = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def loss(params):
        return jnp.float32(0.0)
    """
    assert hits(src, "FTL004") == []


# -- FTL005: branching on traced values --------------------------------------

def test_ftl005_if_on_traced_value():
    src = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def clip(x):
        if jnp.max(x) > 1.0:
            return x / jnp.max(x)
        return x
    """
    assert hits(src, "FTL005") == [("FTL005", 6)]


def test_ftl005_host_coercion_branch():
    src = """\
    import jax.numpy as jnp

    def supervise(loss_history):
        if float(jnp.mean(loss_history)) > 10.0:
            return "rollback"
        return "ok"
    """
    assert hits(src, "FTL005") == [("FTL005", 4)]
    # the coercion inside the claimed test is NOT double-reported
    assert hits(src, "FTL001") == []


def test_ftl005_negative_static_branches():
    """Static config flags, shape metadata, and None checks are the
    legal Python branches traced code is built from."""
    src = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fwd(x, w, mask=None):
        if x.ndim == 3:
            x = x.reshape(-1, x.shape[-1])
        if mask is not None:
            x = x * mask
        if isinstance(w, dict):
            w = w["kernel"]
        return jnp.dot(x, w)
    """
    assert hits(src, "FTL005") == []


# -- suppressions & baseline -------------------------------------------------

def test_suppression_requires_justification():
    src = """\
    import jax.numpy as jnp

    def a(x):
        return float(jnp.sum(x))  # lint: disable=FTL001

    def b(x):
        # lint: disable=FTL001 — one-shot setup scalar, not per-round
        return float(jnp.sum(x))
    """
    # bare disable is inert (a); justified disable suppresses (b)
    assert hits(src, "FTL001") == [("FTL001", 4)]


def test_suppression_parsing():
    by_line = suppressions_for_source(
        "x = 1  # lint: disable=FTL001,FTL005 — measured, accepted\n")
    assert by_line[1] == {"FTL001", "FTL005"}
    assert by_line[2] == {"FTL001", "FTL005"}  # covers the line below


def test_baseline_roundtrip(tmp_path):
    src = textwrap.dedent("""\
    import jax.numpy as jnp

    def a(x):
        return float(jnp.sum(x))
    """)
    findings = analyze_source(src, "mod.py")
    assert len(findings) == 1
    path = tmp_path / "baseline.json"
    save_baseline(str(path), findings)
    base = load_baseline(str(path))
    new, matched = diff_against_baseline(findings, base)
    assert new == [] and matched == 1
    # fingerprints are line-number independent: shifting the module
    # down two lines must not produce a "new" finding
    shifted = analyze_source("\n\n" + src, "mod.py")
    new2, _ = diff_against_baseline(shifted, base)
    assert new2 == []


# -- traced-context discovery: scan/while bodies as LOCAL CLOSURES ----------
# The PR 11 round_program.py idiom: the loop body is built by a
# factory / bound to a local name before the tracing call. Direct and
# partial decoration and direct call-site passing were always modeled;
# these fixtures pin the binding-resolution extension (ISSUE 13).

def test_scan_body_from_closure_factory_bound_to_local():
    """`step = _make_body(t)` then `lax.scan(step, ...)` — the factory
    RESULT is the traced body, reached through the binding map."""
    src = """\
    import jax
    import numpy as np

    def _make_body(c):
        def body(carry, x):
            v = np.sqrt(carry)
            return carry + v * c, x
        return body

    def driver(init, xs):
        step = _make_body(2.0)
        return jax.lax.scan(step, init, xs)
    """
    assert hits(src, "FTL002") == [("FTL002", 6)]


def test_while_loop_bodies_as_name_assigned_lambdas():
    src = """\
    import jax
    import numpy as np

    def run(x):
        body = lambda s: (s[0] + np.exp(s[0]), s[1] + 1)
        cond = lambda s: s[1] < 4
        return jax.lax.while_loop(cond, body, (x, 0))
    """
    assert hits(src, "FTL002") == [("FTL002", 5)]


def test_scan_body_rebound_conditionally():
    """`fn = a_body if flag else b_body` — both candidates trace."""
    src = """\
    import jax
    import numpy as np

    def a_body(c, x):
        return c + np.log(c), x

    def b_body(c, x):
        return c * 2, x

    def driver(init, xs, flag):
        fn = a_body if flag else b_body
        return jax.lax.scan(fn, init, xs)
    """
    assert hits(src, "FTL002") == [("FTL002", 5)]


def test_factory_returning_call_result_is_not_traced():
    """Negative control for the binding resolution: a helper that
    returns a CALL RESULT (not a function) must not mark itself or
    its callees traced — `params = run_ascent(...)` is data flow, not
    closure passing (the over-binding that would cascade false
    FTL005s through the intra-module call graph)."""
    src = """\
    import jax
    import jax.numpy as jnp
    import numpy as np

    def run_ascent(params, xs):
        if len(xs) > 2:
            return params
        return params

    def driver(params, xs):
        params = run_ascent(params, xs)
        return jax.lax.scan(lambda c, x: (c, x), params, xs)
    """
    assert hits(src) == []


def test_traced_lambda_params_are_device_flavored():
    """A name-assigned lambda marked traced treats its parameters as
    device values, so in-body hazards (host coercions) are caught."""
    src = """\
    import jax

    def run(x):
        body = lambda s: (s[0] + float(s[0]), s[1] + 1)
        cond = lambda s: s[1] < 4
        return jax.lax.while_loop(cond, body, (x, 0))
    """
    assert hits(src, "FTL001") == [("FTL001", 4)]
