"""Long-context inference, sequence-parallel over a device mesh.

The transformer LM's attention can run under either EXACT
sequence-parallel strategy (parallel/sequence.py):

* ring — each device holds one block of queries; K/V blocks rotate
  around the ring via ``ppermute`` (per-device score memory O(T^2/n^2));
* ulysses — two all-to-alls re-shard sequence->heads and back; plain
  attention runs on full sequence for the local head slice.

This example runs a 2048-token context over an 8-way mesh under BOTH
strategies and checks each against single-device dense attention.

Run (no TPU needed):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/03_long_context_attention.py
"""
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedtorch_tpu.utils import honor_platform_env
honor_platform_env()  # respect JAX_PLATFORMS=cpu for device-free runs

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from fedtorch_tpu.models.transformer import TransformerLM, \
    long_context_apply

SEQ_LEN, VOCAB = 2048, 128

devices = jax.devices()
mesh = Mesh(np.asarray(devices), ("sp",))
print(f"sequence axis sharded over {len(devices)} devices")

# 8 heads: ulysses shards heads over the 8-way mesh (ring has no
# head-count requirement)
model = TransformerLM(vocab_size=VOCAB, num_layers=2, num_heads=8,
                      d_model=64, max_len=SEQ_LEN)
tokens = jax.random.randint(jax.random.key(1), (1, SEQ_LEN), 0, VOCAB)
params = model.init(jax.random.key(0), tokens)["params"]

# single-device baseline: ordinary causal attention
logits_full = model.apply({"params": params}, tokens)

for strategy in ("ring", "ulysses"):
    logits = long_context_apply(model, params, tokens, mesh,
                                strategy=strategy)
    err = float(jnp.max(jnp.abs(logits - logits_full)))
    print(f"{strategy:8s}: max |sharded - dense| over "
          f"[1, {SEQ_LEN}, {VOCAB}] logits = {err:.2e}")
    assert err < 1e-3, f"{strategy} diverged from the exact baseline"
print("ok: both sequence-parallel strategies exact at "
      f"{SEQ_LEN} tokens x {len(devices)} shards")
