"""Full-state checkpoint / resume — including what the reference loses.

The reference checkpoints only the server's aggregated model
(logs/checkpoint.py:68-82): client control variates, error-feedback
memory, personal models, and dual weights all restart from zero on
resume. Here the checkpoint is the ENTIRE round state — ServerState +
every client's algorithm aux + the threaded PRNG key — so a resumed run
continues bit-exactly, demonstrated below with SCAFFOLD (whose control
variates are exactly the state the reference would lose).

Also shows AsyncCheckpointer: the same writes from a background thread
(atomic tmp+fsync+rename), so training dispatch never blocks on disk.

Run (no TPU needed):
    JAX_PLATFORMS=cpu python examples/05_checkpoint_resume.py
"""
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedtorch_tpu.utils import honor_platform_env
honor_platform_env()

import jax
import numpy as np

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer
from fedtorch_tpu.utils import (
    AsyncCheckpointer, maybe_resume, save_checkpoint,
)

cfg = ExperimentConfig(
    data=DataConfig(dataset="synthetic", synthetic_dim=20, batch_size=16),
    federated=FederatedConfig(federated=True, num_clients=8,
                              online_client_rate=0.5,
                              algorithm="scaffold",
                              sync_type="local_step"),
    model=ModelConfig(arch="logistic_regression"),
    optim=OptimConfig(lr=0.1, weight_decay=0.0),
    train=TrainConfig(local_step=3),
).finalize()
data = build_federated_data(cfg)
model = define_model(cfg, batch_size=16)
trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)

server, clients = trainer.init_state(jax.random.key(0))
for _ in range(3):
    server, clients, _ = trainer.run_round(server, clients)
print(f"trained to round {int(server.round)} (SCAFFOLD, 8 clients)")

with tempfile.TemporaryDirectory() as tmp:
    # --- synchronous save -------------------------------------------
    save_checkpoint(tmp, server, clients, cfg, best_prec1=0.0,
                    is_best=False)
    print("saved: server params + every client's control variates + rng")

    # --- restore into FRESH state -----------------------------------
    s2, c2 = trainer.init_state(jax.random.key(0))
    s2, c2, _, resumed = maybe_resume(tmp, s2, c2, cfg, None)
    assert resumed and int(s2.round) == 3
    ctrl_a = jax.tree.leaves(clients.aux["control"])
    ctrl_b = jax.tree.leaves(c2.aux["control"])
    err = max(float(abs(np.asarray(a) - np.asarray(b)).max())
              for a, b in zip(ctrl_a, ctrl_b))
    print(f"control variates restored, max err = {err:.1e}")

    # --- the resumed run continues EXACTLY --------------------------
    # (run_round DONATES its inputs; keep the returned states)
    s_cont, c_cont, m1 = trainer.run_round(server, clients)
    s_res, c_res, m2 = trainer.run_round(s2, c2)
    perr = max(float(abs(np.asarray(a) - np.asarray(b)).max())
               for a, b in zip(jax.tree.leaves(s_cont.params),
                               jax.tree.leaves(s_res.params)))
    print(f"round 4 after resume: server-param divergence = {perr:.1e}")
    assert perr == 0.0

with tempfile.TemporaryDirectory() as tmp:
    # --- async: identical bytes, off the critical path --------------
    ck = AsyncCheckpointer()
    ck.save(tmp, s_res, c_res, cfg, best_prec1=0.0, is_best=False)
    ck.close()  # flush before reading back
    s3, c3 = trainer.init_state(jax.random.key(0))
    _, _, _, resumed = maybe_resume(tmp, s3, c3, cfg, None)
    assert resumed
    print("async checkpoint written in the background and resumed")
print("ok: full round state (incl. SCAFFOLD control variates) survives "
      "resume bit-exactly")
