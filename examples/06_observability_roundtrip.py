"""Observability round-trip: one CLI run -> record files -> parsed
tables -> a PNG figure, in a single motion (VERDICT r3 #9).

The pieces are individually unit-tested (utils/logging.py writes the
parseable record lines, tools/records.py parses them back,
tools/plots.py renders comparison figures — the reference's
tools/get_summary.py:100-158 + plot_utils.py pipeline); this example
crosses the whole seam the way a user doing experiment analysis would.

Runs in ~a minute on CPU:
    JAX_PLATFORMS=cpu python examples/06_observability_roundtrip.py
"""
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    workdir = tempfile.mkdtemp(prefix="fedtorch_tpu_obs_")
    ckpt_root = os.path.join(workdir, "checkpoint")

    # 1. A real CLI run (the same entry a shell user invokes): FedAvg
    #    on the synthetic dataset, 6 rounds, evaluated every round so
    #    the record file carries a test trajectory.
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join([REPO,
                                         env.get("PYTHONPATH", "")])
    cmd = [sys.executable, "-m", "fedtorch_tpu.cli",
           "--federated", "True", "--data", "synthetic",
           "--arch", "logistic_regression", "--num_workers", "8",
           "--online_client_rate", "0.5", "--federated_type", "fedavg",
           "--federated_sync_type", "local_step", "--num_comms", "6",
           "--local_step", "2", "--batch_size", "8", "--lr", "0.1",
           "--evaluate", "True", "--eval_freq", "1",
           "--weight_decay", "0.0", "--checkpoint", ckpt_root]
    print("running:", " ".join(cmd))
    subprocess.run(cmd, check=True, env=env, cwd=workdir)

    # 2. Parse every record file under the checkpoint root back into
    #    structured tables (regex round-trip of the logger's formats).
    from fedtorch_tpu.tools.records import parse_records
    runs = parse_records(ckpt_root)
    assert runs, f"no record files found under {ckpt_root}"
    rec = runs[0]["records"]
    print(f"parsed {len(runs)} run(s): {len(rec['train'])} train rows, "
          f"{len(rec['val'])} val rows from {runs[0]['path']}")
    assert rec["val"], "expected evaluated rounds in the record file"

    # 3. Render the test-accuracy trajectory to a PNG.
    from fedtorch_tpu.tools.plots import plot_runs
    out_png = os.path.join(workdir, "test_top1.png")
    plot_runs(runs, metric="top1", mode="test", out_path=out_png,
              title="synthetic FedAvg: test top-1 vs round")
    assert os.path.exists(out_png) and os.path.getsize(out_png) > 0
    print(f"figure written: {out_png}")
    return out_png


if __name__ == "__main__":
    from fedtorch_tpu.utils import honor_platform_env
    honor_platform_env()
    main()
