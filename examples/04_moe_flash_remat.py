"""Scaling levers for the transformer LM: sparse MoE, flash attention,
rematerialization.

Three independent knobs on the same model, composable:

* ``num_experts`` + ``capacity_factor`` — Switch-style top-1 MoE blocks
  with capacity-bounded sparse dispatch: cf× the dense-MLP FLOPs no
  matter how many experts (experts shard over an ``ep`` mesh axis —
  parallel/expert.py); ``moe_aux_weight`` adds the load-balance loss and
  ``routing_fractions`` watches for gate collapse.
* ``attention='flash'`` — fused online-softmax attention (a Pallas
  kernel on TPU, dense fallback elsewhere): O(block²) score memory
  instead of O(T²).
* ``remat=True`` — per-block ``jax.checkpoint``: activation memory
  scales with one block instead of depth, ~1.33× FLOPs.

Run (no TPU needed):
    JAX_PLATFORMS=cpu python examples/04_moe_flash_remat.py
"""
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedtorch_tpu.utils import honor_platform_env
honor_platform_env()

import jax
import jax.numpy as jnp
import numpy as np

from fedtorch_tpu.models.transformer import TransformerLM, \
    routing_fractions

VOCAB, SEQ = 128, 256
tokens = jax.random.randint(jax.random.key(1), (2, SEQ), 0, VOCAB)

# the plain dense model is the numerical baseline
base_kw = dict(vocab_size=VOCAB, d_model=64, num_heads=4, num_layers=2,
               max_len=SEQ)
dense = TransformerLM(**base_kw)
params = dense.init(jax.random.key(0), tokens)["params"]
ref = dense.apply({"params": params}, tokens)

# 1) flash attention: a backend swap — same params, same logits
flash = TransformerLM(**base_kw, attention="flash")
err = float(jnp.max(jnp.abs(flash.apply({"params": params}, tokens)
                            - ref)))
print(f"flash attention: max |flash - dense| = {err:.2e}")
assert err < 1e-4

# 2) remat: same params, same logits, same gradients — only the
#    backward's memory/FLOPs trade changes
remat = TransformerLM(**base_kw, remat=True)
err = float(jnp.max(jnp.abs(remat.apply({"params": params}, tokens)
                            - ref)))
print(f"remat:           max |remat - dense| = {err:.2e}")
assert err < 1e-6

# 3) sparse MoE: 8 experts at drop-free capacity (cf=8.0 here, so no
#    expert can overflow) — the sparse gather/scatter dispatch is EXACT
#    vs the dense (E x FLOPs) dispatch. Production capacities like the
#    cf=1.25 used in step 4 may drop tokens to the residual instead.
moe_kw = dict(base_kw, num_experts=8)
moe_dense = TransformerLM(**moe_kw)                      # E x FLOPs
moe_sparse = TransformerLM(**moe_kw, capacity_factor=8.0)  # no drops
moe_params = moe_dense.init(jax.random.key(0), tokens)["params"]
err = float(jnp.max(jnp.abs(
    moe_sparse.apply({"params": moe_params}, tokens)
    - moe_dense.apply({"params": moe_params}, tokens))))
print(f"sparse MoE (ample capacity): max |sparse - dense| = {err:.2e}")
assert err < 1e-4

fr = routing_fractions(moe_dense, moe_params, tokens)
for block, f in sorted(fr.items()):
    print(f"  {block} routing fractions: "
          f"{np.round(np.asarray(f), 3).tolist()}")

# 4) everything at once — the long-context training configuration
full = TransformerLM(**moe_kw, capacity_factor=1.25, attention="flash",
                     remat=True)
out = full.apply({"params": moe_params}, tokens)
grads = jax.grad(lambda p: jnp.sum(
    full.apply({"params": p}, tokens) ** 2))(moe_params)
finite = all(bool(jnp.all(jnp.isfinite(g)))
             for g in jax.tree.leaves(grads))
print(f"moe+flash+remat composed: logits {tuple(out.shape)}, "
      f"grads finite={finite}")
assert finite
print("ok: all three levers exact/finite, independently and composed")
