"""Quickstart: federated training through the Python API.

The CLI (`python -m fedtorch_tpu.cli` / `run_tpu.py`) wraps exactly this
sequence; use the API directly when embedding the framework in your own
experiment harness.

Runs in ~a minute on CPU:   python examples/01_quickstart_api.py
"""
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedtorch_tpu.utils import honor_platform_env
honor_platform_env()  # respect JAX_PLATFORMS=cpu for device-free runs

import jax

from fedtorch_tpu.algorithms import make_algorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer, evaluate

# 1. Configuration: typed, immutable, validated by finalize()
#    (the reference's ~90 argparse flags live in these dataclasses).
cfg = ExperimentConfig(
    data=DataConfig(dataset="synthetic", synthetic_dim=32, batch_size=16),
    federated=FederatedConfig(
        federated=True, num_clients=16, online_client_rate=0.5,
        algorithm="fedavg", sync_type="local_step"),
    model=ModelConfig(arch="mlp", mlp_num_layers=1, mlp_hidden_size=64),
    optim=OptimConfig(lr=0.1, in_momentum=True),
    train=TrainConfig(local_step=5),
).finalize()

# 2. Data: per-client shards stacked into [clients, rows, ...] arrays.
#    Non-IID partitioners (label-sort, Dirichlet, natural federation)
#    are selected by cfg.data / cfg.federated fields.
data = build_federated_data(cfg)

# 3. Model + algorithm + trainer. The trainer compiles ONE XLA program
#    for the whole communication round: client sampling, the local-SGD
#    scan, and the aggregation collective.
model = define_model(cfg, batch_size=cfg.data.batch_size)
trainer = FederatedTrainer(cfg, model, make_algorithm(cfg), data.train)

# 4. Train. run_round is one jitted call; fit() loops it.
server, clients = trainer.init_state(jax.random.key(0))
for r in range(10):
    server, clients, metrics = trainer.run_round(server, clients)
    online = metrics.online_mask.sum()
    loss = (metrics.train_loss.sum() / online).item()
    print(f"round {r}: mean online train loss {loss:.4f}")

# 5. Evaluate the aggregated server model on the server-side test set.
ev = evaluate(model, server.params, data.test_x, data.test_y)
print(f"final: test loss {float(ev.loss):.4f}  "
      f"top-1 {100 * float(ev.top1):.1f}%")
