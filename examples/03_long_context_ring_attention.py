"""Long-context inference with ring attention over a device mesh.

The transformer LM's attention can run as EXACT ring attention
(parallel/sequence.py): the sequence axis is sharded over the mesh, each
device holds one block of queries, and key/value blocks rotate around the
ring via ``ppermute`` — attention memory per device drops from O(T^2) to
O(T * T/n) with no approximation. On a TPU pod the rotation rides ICI.

This example runs a 2048-token context over an 8-way sequence-parallel
mesh and checks the sharded result against single-device attention.

Run (no TPU needed):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/03_long_context_ring_attention.py
"""
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedtorch_tpu.utils import honor_platform_env
honor_platform_env()  # respect JAX_PLATFORMS=cpu for device-free runs

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from fedtorch_tpu.models.transformer import TransformerLM, \
    long_context_apply

SEQ_LEN, VOCAB = 2048, 128

devices = jax.devices()
mesh = Mesh(np.asarray(devices), ("sp",))
print(f"sequence axis sharded over {len(devices)} devices")

model = TransformerLM(vocab_size=VOCAB, num_layers=2, num_heads=4,
                      d_model=64, max_len=SEQ_LEN)
tokens = jax.random.randint(jax.random.key(1), (1, SEQ_LEN), 0, VOCAB)
params = model.init(jax.random.key(0), tokens)["params"]

# sharded: every attention block runs exact ring attention over the mesh
logits_ring = long_context_apply(model, params, tokens, mesh)

# single-device baseline: ordinary causal attention
logits_full = model.apply({"params": params}, tokens)

err = float(jnp.max(jnp.abs(logits_ring - logits_full)))
print(f"max |ring - full| over [1, {SEQ_LEN}, {VOCAB}] logits: {err:.2e}")
assert err < 1e-3, "ring attention diverged from the exact baseline"
print("ok: exact long-context attention, sequence-parallel over the mesh")
