"""Writing your own federated algorithm: FedNova in ~30 lines.

The engine treats an algorithm as a set of pure hooks on
``FedAlgorithm`` (algorithms/base.py) — aux-state init, in-loop gradient
transforms, payload construction, the server step. Every built-in
(SCAFFOLD, FedGATE, DRFA, ...) is built from these same hooks, so a new
algorithm needs only the hooks it changes; the engine supplies the jitted
round program, client sampling, sharding, and wire formats.

FedNova (Wang et al. 2020, "Tackling the Objectivity Inconsistency
Problem") normalizes each client's model delta by its own effective
number of local steps before averaging, then rescales the aggregated
update by the mean step count — removing the bias that heterogeneous
local-step counts (epoch-sync mode with skewed shard sizes) introduce
into plain FedAvg. Here that is TWO small hook overrides.

Run:   python examples/02_custom_algorithm.py
"""
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedtorch_tpu.utils import honor_platform_env
honor_platform_env()  # respect JAX_PLATFORMS=cpu for device-free runs

import jax
import jax.numpy as jnp

from fedtorch_tpu.algorithms.base import FedAlgorithm
from fedtorch_tpu.config import (
    DataConfig, ExperimentConfig, FederatedConfig, ModelConfig,
    OptimConfig, TrainConfig,
)
from fedtorch_tpu.core.state import tree_scale
from fedtorch_tpu.data import build_federated_data
from fedtorch_tpu.models import define_model
from fedtorch_tpu.parallel import FederatedTrainer


class FedNova(FedAlgorithm):
    """Normalized averaging: payload_i = w_i * delta_i / tau_i, and the
    server applies sum_i(payload_i) scaled by the weighted mean tau."""

    name = "fednova"

    def client_payload(self, *, delta, client_aux, params, server_params,
                       server_aux, lr, local_steps, weight,
                       full_loss=None):
        # local_steps is THIS client's effective step count (its
        # epoch-sync budget under skew, or the static K) — exactly
        # FedNova's tau_i. Ship the normalized, weighted delta plus the
        # weighted tau so the server can recover the mean step count.
        tau = jnp.maximum(local_steps.astype(jnp.float32), 1.0)
        payload = tree_scale(delta, weight / tau)
        return {"delta": payload, "wtau": weight * tau}, client_aux

    def server_update(self, server_params, server_opt, server_aux,
                      payload_sum, *, online_idx, num_online_eff,
                      client_losses=None):
        # rescale by the weighted-mean tau, then reuse the standard
        # dual-mode server step (p -= lr_scale_at_sync * d).
        update = tree_scale(payload_sum["delta"], payload_sum["wtau"])
        return super().server_update(
            server_params, server_opt, server_aux, update,
            online_idx=online_idx, num_online_eff=num_online_eff,
            client_losses=client_losses)


def run(algorithm_cls, steps_skew: bool):
    cfg = ExperimentConfig(
        data=DataConfig(dataset="synthetic", synthetic_dim=32,
                        batch_size=8),
        federated=FederatedConfig(
            federated=True, num_clients=8, online_client_rate=1.0,
            algorithm="fedavg",
            # epoch-sync over the synthetic dataset's lognormal shard
            # sizes = heterogeneous local step counts, the regime
            # FedNova corrects
            sync_type="epoch" if steps_skew else "local_step",
            num_epochs_per_comm=1),
        model=ModelConfig(arch="mlp", mlp_num_layers=1,
                          mlp_hidden_size=32),
        optim=OptimConfig(lr=0.05),
        train=TrainConfig(local_step=4),
    ).finalize()
    data = build_federated_data(cfg)
    model = define_model(cfg, batch_size=cfg.data.batch_size)
    trainer = FederatedTrainer(cfg, model, algorithm_cls(cfg), data.train)
    server, clients = trainer.init_state(jax.random.key(0))
    loss = float("nan")
    for _ in range(15):
        server, clients, m = trainer.run_round(server, clients)
        loss = float(m.train_loss.sum() / m.online_mask.sum())
    return loss


if __name__ == "__main__":
    for skew in (False, True):
        regime = "skewed epoch-sync" if skew else "uniform local steps"
        base = run(FedAlgorithm, skew)
        nova = run(FedNova, skew)
        print(f"{regime:22s}: fedavg loss {base:.4f}   "
              f"fednova loss {nova:.4f}")
